"""Mixture-of-Experts with expert parallelism.

**Net-new capability** — the reference has no MoE layers or expert
parallelism at all; only the raw `alltoall` collective exists
(`operators/collective/alltoall_op.cu.cc`, SURVEY.md §2.3 "Expert parallel
(EP/MoE): ABSENT").  This module supplies the capability the TPU way:

- GShard/Switch-style top-k gating with capacity-factor token dropping,
  expressed as dense one-hot dispatch/combine einsums (static shapes — no
  scatter with data-dependent sizes, so it jits and runs on the MXU).
- Expert parallelism via `lax.all_to_all` over an ``'ep'`` mesh axis inside
  `shard_map`: tokens are exchanged so each device runs only its local
  experts, then routed back — the classic MoE all-to-all pair riding ICI.
- Without a mesh the same math runs single-device (n_ep = 1, no
  collectives), so `MoELayer` works eagerly too.

Auxiliary load-balancing loss follows Shazeer et al. (mean gate fraction ×
mean dispatch fraction × num_experts).
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ....core.dispatch import dispatch
from ....core.tensor import Tensor, unwrap
from ....nn import initializer as init
from ....nn.layer.layers import Layer

__all__ = ["MoELayer", "moe_gating", "moe_forward"]


def moe_gating(logits, k: int, capacity: int):
    """Top-k gating -> (dispatch [T,E,C] bool, combine [T,E,C] float, aux).

    logits: [T, E] raw gate scores.  Tokens beyond an expert's capacity C
    are dropped (their combine weights are zero), per GShard.
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)  # [T, E]

    dispatch = jnp.zeros((t, e), jnp.float32)  # accumulated choice masks
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    gates = jnp.zeros((t, e), jnp.float32)
    masked = probs
    prev_counts = jnp.zeros((e,), jnp.int32)  # slots used by earlier choices
    for _ in range(k):
        idx = jnp.argmax(masked, axis=-1)  # [T]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)  # [T, E]
        # position of each token within its chosen expert's buffer
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # [T, E]
        pos = pos + prev_counts[None, :].astype(jnp.float32) * onehot
        fits = (pos < capacity) & (onehot > 0)
        posc = jnp.clip(pos.astype(jnp.int32), 0, capacity - 1)
        slot = jax.nn.one_hot(posc, capacity, dtype=jnp.float32) * \
            fits.astype(jnp.float32)[..., None]  # [T, E, C]
        gate_val = (probs * onehot).sum(-1, keepdims=True)  # [T, 1]
        combine = combine + slot * gate_val[..., None]
        dispatch = dispatch + onehot
        prev_counts = prev_counts + onehot.sum(0).astype(jnp.int32)
        masked = masked * (1.0 - onehot)  # exclude chosen expert next round

    if k > 1:
        # renormalize combine weights over the selected experts
        denom = combine.sum(axis=(1, 2), keepdims=True)
        combine = combine / jnp.maximum(denom, 1e-9)
    # k == 1 keeps the raw gate probability as the scale (Switch
    # Transformer): renormalizing would cancel it exactly and starve the
    # router of task-loss gradient
    dispatch_mask = combine > 0.0

    # aux load-balancing loss (Shazeer): E * mean_frac_tokens * mean_prob
    frac_tokens = dispatch.mean(axis=0) / k  # [E]
    frac_probs = probs.mean(axis=0)  # [E]
    aux = (frac_tokens * frac_probs).sum() * e
    return dispatch_mask, combine, aux


def moe_forward(x, gate_w, w1, b1, w2, b2, *, k=2, capacity_factor=1.25,
                axis_name: Optional[str] = None, activation=jax.nn.gelu):
    """Functional MoE FFN.

    x: [T, H] local tokens; gate_w: [H, E_total];
    w1: [E_local, H, F], b1: [E_local, F], w2: [E_local, F, H],
    b2: [E_local, H].  With `axis_name` set (inside shard_map), experts are
    sharded over that axis: E_total = n_ep * E_local and tokens ride two
    all_to_alls.  Returns (out [T, H], aux_loss scalar).
    """
    t, h = x.shape
    e_local = w1.shape[0]
    n_ep = lax.axis_size(axis_name) if axis_name else 1
    e_total = gate_w.shape[1]
    assert e_total == n_ep * e_local, (e_total, n_ep, e_local)

    capacity = max(1, math.ceil(t * k / e_total * capacity_factor))
    logits = x @ gate_w  # [T, E_total]
    dispatch_mask, combine, aux = moe_gating(logits, k, capacity)

    # dispatch tokens into per-expert buffers: [E_total, C, H]
    buf = jnp.einsum("tec,th->ech", dispatch_mask.astype(x.dtype), x)

    if axis_name:
        # [E_total, C, H] -> each device keeps its local experts' buffers
        # from every peer: exchange over 'ep'
        buf = buf.reshape(n_ep, e_local, capacity, h)
        buf = lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=0,
                             tiled=False)  # [n_ep, e_local, C, H] peers' tokens
        buf = buf.transpose(1, 0, 2, 3).reshape(e_local, n_ep * capacity, h)
    else:
        buf = buf.reshape(e_local, capacity, h)

    # expert FFN (batched over local experts — one big MXU einsum)
    hdn = activation(jnp.einsum("ech,ehf->ecf", buf, w1) + b1[:, None, :])
    out = jnp.einsum("ecf,efh->ech", hdn, w2) + b2[:, None, :]

    if axis_name:
        out = out.reshape(e_local, n_ep, capacity, h).transpose(1, 0, 2, 3)
        out = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                             tiled=False)
        out = out.reshape(e_total, capacity, h)

    # combine back to token order weighted by gate values
    y = jnp.einsum("tec,ech->th", combine.astype(x.dtype), out)
    return y, aux


class MoELayer(Layer):
    """Mixture-of-experts FFN layer (net-new; no reference counterpart).

    Drop-in replacement for a transformer MLP block.  `num_experts` is the
    GLOBAL expert count.  Two expert-parallel modes:

    - **GSPMD (fleet) path** — keep ``ep_degree=1`` and set `ep_axis` to an
      existing mesh axis (e.g. ``"mp"``): parameters are logically
      full-size, tagged ``mesh_axes=(ep_axis, ...)``, and the jit'd
      ShardedTrainStep's partitioner shards the expert dimension and
      inserts the all-to-alls itself.  The forward runs the dense math
      (axis_name=None) — correct for logically-global params.
    - **shard_map path** — pass ``ep_degree = n`` and `ep_axis` naming the
      shard_map mesh axis: each device holds ``num_experts // ep_degree``
      expert FFNs and `moe_forward` issues explicit `lax.all_to_all`s.
    """

    def __init__(self, hidden_size, intermediate_size, num_experts,
                 k=2, capacity_factor=1.25, ep_axis=None, ep_degree=1,
                 weight_attr=None, name=None):
        super().__init__()
        if num_experts % ep_degree:
            raise ValueError("num_experts must divide by ep_degree")
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_experts = num_experts
        self.k = k
        self.capacity_factor = capacity_factor
        self.ep_axis = ep_axis
        e_local = num_experts // ep_degree
        std = 0.02
        self.gate_weight = self.create_parameter(
            [hidden_size, num_experts], attr=weight_attr,
            default_initializer=init.Normal(0.0, std))
        self.w1 = self.create_parameter(
            [e_local, hidden_size, intermediate_size],
            default_initializer=init.Normal(0.0, std))
        self.b1 = self.create_parameter(
            [e_local, intermediate_size], is_bias=True)
        self.w2 = self.create_parameter(
            [e_local, intermediate_size, hidden_size],
            default_initializer=init.Normal(0.0, std))
        self.b2 = self.create_parameter([e_local, hidden_size], is_bias=True)
        if self.ep_axis:
            ax = self.ep_axis
            for p, spec in ((self.w1, (ax, None, None)),
                            (self.b1, (ax, None)),
                            (self.w2, (ax, None, None)),
                            (self.b2, (ax, None))):
                p.mesh_axes = spec
        self._aux_loss = None

    def forward(self, x):
        shape = x.shape
        flat = x.reshape([-1, self.hidden_size])

        def f(xv, gw, w1, b1, w2, b2):
            # axis_name only when actually inside a shard_map over ep_axis;
            # under plain jit/GSPMD params are logically global and the
            # dense path + sharding annotations are the correct program
            return moe_forward(
                xv, gw, w1, b1, w2, b2, k=self.k,
                capacity_factor=self.capacity_factor,
                axis_name=self.ep_axis if _axis_in_scope(self.ep_axis)
                else None)

        out, aux = dispatch(f, flat, self.gate_weight, self.w1, self.b1,
                            self.w2, self.b2)
        self._aux_loss = aux
        return out.reshape(shape)

    @property
    def aux_loss(self):
        """Load-balancing auxiliary loss from the last forward."""
        return self._aux_loss


def _axis_in_scope(name) -> bool:
    if not name:
        return False
    try:
        lax.axis_size(name)
        return True
    except Exception:
        return False
