"""Pipeline parallelism.

Reference: dygraph `PipelineLayer` (`meta_parallel/parallel_layers/
pp_layers.py:76`, `SegmentLayers` `:23`) and `PipelineParallel.train_batch`
(`meta_parallel/pipeline_parallel.py:109`) with p2p send/recv; static-side
1F1B schedule in `framework/section_worker.cc:144`.

TPU-native plan (SURVEY.md §7 row "send_v2/recv_v2 PP"): homogeneous
transformer blocks are stacked along a leading axis sharded over the 'pp'
mesh axis; the microbatch schedule runs inside one jit using shard_map +
`lax.ppermute` to rotate activations between stages (see
`paddle_tpu/parallel/pipeline.py` for the schedule kernel).  This module
provides the reference-compatible Layer descriptions and a driver that:

* single-controller eager mode — executes stages sequentially with
  micro-batch accumulation (semantically identical; pp=1 collapse), and
* under `fleet.build_train_step` with pp>1 — routes to the shard_map
  schedule.
"""
from __future__ import annotations

import math
from typing import Callable, List, Optional

from ....core.tensor import Tensor
from ....nn.layer.layers import Layer


class LayerDesc:
    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr="weight",
                 *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """reference pp_layers.py:23 — uniform or weighted layer->stage cut."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self.layers_desc = layers_desc
        self.num_parts = num_parts
        self.method = method

    def do_segment(self) -> List[int]:
        n = len(self.layers_desc)
        if self.method == "uniform":
            return self.uniform(n, self.num_parts)
        raise NotImplementedError(self.method)

    @staticmethod
    def uniform(num_items, num_parts):
        result = [0]
        part = num_items / num_parts
        for i in range(1, num_parts + 1):
            result.append(int(math.floor(i * part)))
        result[-1] = num_items
        return result


class PipelineLayer(Layer):
    """reference pp_layers.py:76 — model described as a flat list of layer
    descs, segmented into stages."""

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 **kwargs):
        super().__init__()
        self._loss_fn = loss_fn
        self._num_stages = num_stages or 1
        self._recompute_interval = recompute_interval
        self.descs = list(layers)
        seg = SegmentLayers(self.descs, self._num_stages, seg_method)
        self.segment_parts = seg.do_segment()
        # single-controller: materialize ALL stages (each stage's params are
        # sharded over 'pp' by the schedule kernel when pp>1)
        from ....nn.layer.container import LayerList

        built = []
        for d in self.descs:
            if isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer):
                built.append(d)
            elif callable(d):
                built.append(_FuncLayer(d))
            else:
                raise TypeError(f"bad layer desc {d!r}")
        self.run_function = LayerList(built)

    def get_stage_layers(self, stage):
        lo, hi = self.segment_parts[stage], self.segment_parts[stage + 1]
        return list(self.run_function)[lo:hi]

    def forward(self, *args):
        x = args[0] if len(args) == 1 else args
        for layer in self.run_function:
            x = layer(x) if not isinstance(x, tuple) else layer(*x)
        return x


class _FuncLayer(Layer):
    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, *args):
        return self._fn(*args)


class PipelineParallel(Layer):
    """reference `pipeline_parallel.py:109` train_batch: micro-batch loop
    with F-then-B (this fork's dygraph PP) + DP grad sync + optimizer step.

    Single-controller semantics: micro-batches accumulate gradients and one
    optimizer step is taken — numerically identical to the reference's
    schedule; with pp>1 the compiled path runs the 1F1B shard_map kernel
    (`paddle_tpu.parallel.pipeline`)."""

    def __init__(self, layers: PipelineLayer, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        acc = 1
        if strategy is not None:
            acc = int(strategy.pipeline_configs.get("accumulate_steps", 1))
        self.accumulate_steps = acc
        self._compiled_step = None

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def _pp_mesh(self):
        mesh = getattr(self._hcg, "mesh", None) if self._hcg else None
        if mesh is not None and int(mesh.shape.get("pp", 1)) > 1 and \
                int(mesh.shape.get("pp", 1)) == self._layers._num_stages:
            return mesh
        return None

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        from ....ops import split as tsplit

        inputs, labels = data
        # compiled 1F1B path: whole schedule + optimizer in one SPMD
        # program, per-stage params sharded over 'pp' (section_worker 1F1B).
        # The user's accumulate_steps IS the micro-batch count (schedule
        # bubbles simply grow when it is < pp); batches the micro split
        # cannot divide fall back to the eager loop instead of erroring.
        mesh = self._pp_mesh()
        if mesh is not None and scaler is None:
            n_micro = max(1, self.accumulate_steps)
            dp = int(mesh.shape.get("dp", 1))
            bsz = inputs.shape[0] if hasattr(inputs, "shape") else None
            if bsz is not None and bsz % (n_micro * dp) == 0:
                if self._compiled_step is None:
                    from ..pipeline_step import PipelineTrainStep

                    self._compiled_step = PipelineTrainStep(
                        self._layers, self._layers._loss_fn, optimizer,
                        mesh, n_micro=n_micro)
                loss = self._compiled_step(inputs, labels)
                if lr_scheduler is not None:
                    lr_scheduler.step()
                return loss
        k = self.accumulate_steps
        total = None
        micro_in = tsplit(inputs, k, axis=0) if k > 1 else [inputs]
        micro_lab = tsplit(labels, k, axis=0) if k > 1 else [labels]
        for x, y in zip(micro_in, micro_lab):
            out = self._layers(x)
            loss = self._layers._loss_fn(out, y)
            if scaler is not None:
                scaler.scale(loss / k).backward()
            else:
                (loss / k).backward()
            total = loss if total is None else total + loss
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total / k

    def state_dict(self, *args, **kwargs):
        # master params live in the compiled step's packed copy
        if self._compiled_step is not None:
            self._compiled_step.sync_params()
        return self._layers.state_dict(*args, **kwargs)

    def eval_batch(self, data, compute_loss=True):
        if self._compiled_step is not None:
            self._compiled_step.sync_params()
        inputs, labels = data
        out = self._layers(inputs)
        if compute_loss and self._layers._loss_fn is not None:
            return self._layers._loss_fn(out, labels)
        return out
