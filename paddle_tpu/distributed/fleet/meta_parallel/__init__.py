from .mp_layers import (ColumnParallelLinear, ParallelCrossEntropy,
                        RowParallelLinear, TensorParallel,
                        VocabParallelEmbedding)
from .moe_layer import MoELayer, moe_forward, moe_gating
from .pipeline_parallel import (LayerDesc, PipelineLayer, PipelineParallel,
                                SegmentLayers, SharedLayerDesc)
