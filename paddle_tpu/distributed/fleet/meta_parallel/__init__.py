from .mp_layers import (ColumnParallelLinear, ParallelCrossEntropy,
                        RowParallelLinear, TensorParallel,
                        VocabParallelEmbedding)
from .pipeline_parallel import (LayerDesc, PipelineLayer, PipelineParallel,
                                SegmentLayers, SharedLayerDesc)
