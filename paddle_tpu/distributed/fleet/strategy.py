"""DistributedStrategy.

Reference: `paddle/fluid/framework/distributed_strategy.proto:158-209` (30+
switches: amp, recompute, sharding, pipeline, tensor_parallel, hybrid
degrees, gradient_merge, dgc, localsgd, a_sync...) with the Python facade
`fleet/base/distributed_strategy.py:120`.
"""
from __future__ import annotations

import json


class DistributedStrategy:
    def __init__(self):
        # mirrors the proto's switch set; unsupported-on-TPU entries are kept
        # for config compatibility and validated at fleet.init
        self.amp = False
        self.amp_configs = {"init_loss_scaling": 32768.0, "use_pure_fp16": False,
                            "use_bf16": True}
        self.recompute = False
        self.recompute_configs = {"checkpoints": []}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.sharding = False
        self.sharding_configs = {"stage": 1, "sharding_degree": 1,
                                 "segment_broadcast_MB": 32.0, "offload": False}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1,
                                 "schedule_mode": "1F1B"}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {"tensor_parallel_degree": 1}
        self.sequence_parallel = False
        self.sequence_parallel_configs = {"sequence_parallel_degree": 1}
        self.expert_parallel = False
        self.expert_parallel_configs = {"expert_parallel_degree": 1}
        self.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                               "sharding_degree": 1, "sp_degree": 1}
        self.lamb = False
        self.lars = False
        self.localsgd = False
        self.localsgd_configs = {"k_steps": 4}
        self.dgc = False
        self.dgc_configs = {"rampup_begin_step": 0, "sparsity": [0.999]}
        self.fp16_allreduce = False
        self.lookahead = False
        self.lookahead_configs = {"alpha": 0.5, "k": 5}
        self.a_sync = False
        self.a_sync_configs = {"k_steps": -1}
        self.heter_ccl_mode = False
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.gradient_scale_configs = {"scale_strategy": "avg"}
        self.elastic = False
        self.auto = False

    def to_dict(self):
        return {k: v for k, v in self.__dict__.items()}

    def save_to_prototxt(self, output):
        with open(output, "w") as f:
            json.dump(self.to_dict(), f, indent=2)

    def load_from_prototxt(self, pb_file):
        with open(pb_file) as f:
            for k, v in json.load(f).items():
                setattr(self, k, v)

    def __repr__(self):
        on = [k for k, v in self.__dict__.items()
              if isinstance(v, bool) and v]
        return f"DistributedStrategy(enabled={on}, hybrid={self.hybrid_configs})"
