"""Hybrid parallel topology on a named-axis device mesh.

Reference: `python/paddle/distributed/fleet/base/topology.py:36`
(CommunicateTopology — N-D cartesian rank mesh) and `:117`
(HybridCommunicateGroup — builds dp/mp/pp/sharding communication groups).

TPU-native: the N-D rank grid IS a `jax.sharding.Mesh` with named axes
('dp','pp','sp','mp', plus 'sharding' folded into dp).  There are no
explicit communicator rings — a "group" is just a mesh axis name, and XLA
inserts the collectives (SURVEY.md §2.3 row 1).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec


# canonical axis order: outermost (slowest, cross-ICI-friendly last) first.
# dp outermost so data-parallel gradient reduction rides the full mesh;
# mp innermost so tensor-parallel collectives use nearest neighbors.
AXIS_ORDER = ("dp", "pp", "sp", "mp")


class CommunicateTopology:
    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "model"),
                 dims=(1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = None
        self._world = int(np.prod(dims))

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    def world_size(self):
        return self._world

    def get_rank(self, **args):
        idx = [args[n] for n in self._parallel_names]
        return int(np.ravel_multi_index(idx, self._dims))

    def get_coord(self, rank):
        return tuple(int(i) for i in np.unravel_index(rank, self._dims))

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        ranks = []
        for r in range(self._world):
            if self.get_coord(r)[axis] == index:
                ranks.append(r)
        return ranks

    def get_comm_list(self, axis_name):
        """List of rank-groups along `axis_name` (reference topology.py)."""
        axis = self._parallel_names.index(axis_name)
        other_dims = [d for i, d in enumerate(self._dims) if i != axis]
        groups = []
        for flat in range(int(np.prod(other_dims)) if other_dims else 1):
            coords = list(np.unravel_index(flat, other_dims)) if other_dims else []
            group = []
            for k in range(self._dims[axis]):
                full = coords[:axis] + [k] + coords[axis:]
                group.append(self.get_rank(**dict(zip(self._parallel_names, full))))
            groups.append(group)
        return groups


def build_mesh(dp: int = 1, pp: int = 1, sp: int = 1, mp: int = 1,
               devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices) if devices is not None else jax.devices()
    need = dp * pp * sp * mp
    if need > len(devices):
        raise ValueError(
            f"mesh {dp}x{pp}x{sp}x{mp}={need} exceeds {len(devices)} devices"
        )
    arr = np.asarray(devices[:need]).reshape(dp, pp, sp, mp)
    return Mesh(arr, AXIS_ORDER)


class HybridCommunicateGroup:
    """reference `topology.py:117`: owns the 4-D topology and exposes
    per-strategy group info.  Group handles are mesh axis names."""

    def __init__(self, topology: CommunicateTopology = None, mesh: Mesh = None,
                 dp=1, pp=1, sp=1, mp=1, sharding=1):
        if mesh is None:
            # sharding degree folds into dp for mesh purposes (ZeRO shards
            # optimizer state over the data axis, reference sharding_optimizer)
            mesh = build_mesh(dp=dp * sharding, pp=pp, sp=sp, mp=mp)
        self._mesh = mesh
        self._dp = int(mesh.shape["dp"])
        self._pp = int(mesh.shape["pp"])
        self._sp = int(mesh.shape["sp"])
        self._mp = int(mesh.shape["mp"])
        self._sharding = sharding
        self._topo = CommunicateTopology(
            ("data", "pipe", "sharding", "model"),
            (self._dp // max(sharding, 1), self._pp, max(sharding, 1), self._mp),
        )
        self.global_rank = jax.process_index()

    @property
    def mesh(self) -> Mesh:
        return self._mesh

    # -- degree queries (reference API) -------------------------------------
    def get_data_parallel_world_size(self):
        return self._dp

    def get_model_parallel_world_size(self):
        return self._mp

    def get_pipe_parallel_world_size(self):
        return self._pp

    def get_sharding_parallel_world_size(self):
        return self._sharding if self._sharding > 1 else self._dp

    def get_sequence_parallel_world_size(self):
        return self._sp

    def get_parallel_mode(self):
        if self._mp == 1 and self._pp == 1 and self._sharding <= 1:
            return "data_parallel"
        if self._sharding > 1 and self._mp == 1 and self._pp == 1:
            return "sharding_parallel"
        if self._mp > 1 and self._pp == 1:
            return "tensor_parallel"
        return "pipeline_parallel"

    # rank queries are meaningful per-process in multi-host runs
    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    # -- sharding helpers ---------------------------------------------------
    def named_sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self._mesh, PartitionSpec(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self._mesh, PartitionSpec())

    def data_sharding(self, rest_ndim: int = 0) -> NamedSharding:
        return NamedSharding(self._mesh, PartitionSpec("dp"))

    def topology(self):
        return self._topo


_HCG: Optional[HybridCommunicateGroup] = None


def set_hybrid_communicate_group(hcg: HybridCommunicateGroup):
    global _HCG
    _HCG = hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _HCG
