"""paddle.distributed.spawn (reference `distributed/spawn.py`).

In the single-controller TPU model one process drives all local chips, so
`spawn(fn, nprocs=-1)` simply runs `fn` once in-process.  Multi-host spawn
launches one process per host via multiprocessing when explicitly requested
(each child must set PADDLE_TRAINER_ID / COORDINATOR_ADDRESS).
"""
from __future__ import annotations

import os


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    if nprocs in (-1, 0, 1):
        func(*args)
        return None
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        env = {"PADDLE_TRAINER_ID": str(rank),
               "PADDLE_TRAINERS_NUM": str(nprocs)}
        p = ctx.Process(target=_entry, args=(func, args, env), daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
    return procs


def _entry(func, args, env):
    os.environ.update(env)
    func(*args)
