"""`paddle.distributed` equivalent (SURVEY.md §2.3)."""
from . import collective
from .collective import (Group, ReduceOp, all_gather, all_gather_object,
                         all_reduce, alltoall, barrier, broadcast, irecv,
                         isend, new_group, recv, reduce, reduce_scatter,
                         scatter, send, split, wait)
from .parallel import (ParallelEnv, get_rank, get_world_size,
                       init_parallel_env)
from .topology import (CommunicateTopology, HybridCommunicateGroup,
                       build_mesh, get_hybrid_communicate_group,
                       set_hybrid_communicate_group)
from . import fleet
from . import ps
from .fleet.data_parallel import DataParallel
from . import spawn as _spawn_mod
from .spawn import spawn
