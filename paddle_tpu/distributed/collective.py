"""Collective communication API.

Reference: `python/paddle/distributed/collective.py:346-1576`
(all_reduce/all_gather/broadcast/reduce/scatter/alltoall/send/recv/barrier
over per-ring NCCL communicators) and the static-graph `c_*` collective op
library (`paddle/fluid/operators/collective/`).

TPU-native (SURVEY.md §5 "Distributed communication backend"): a group is a
mesh axis name.  Inside a `shard_map`/pjit trace these lower to XLA
collectives over ICI (`lax.psum`, `all_gather`, `ppermute`, `all_to_all`);
in the eager single-controller mode data is either replicated (collectives
are the identity) or the API operates on a sharded global array, where XLA
already holds the global view — matching the semantics the reference gets
from NCCL ranks.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..core.dispatch import dispatch
from ..core.tensor import Tensor, unwrap


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A communication group = a mesh axis name (replaces ring_id)."""

    def __init__(self, axis_name: str = "dp", ranks=None, id=0):
        self.axis_name = axis_name
        self.ranks = ranks
        self.id = id

    @property
    def nranks(self):
        from .topology import get_hybrid_communicate_group

        hcg = get_hybrid_communicate_group()
        if hcg is not None and self.axis_name in hcg.mesh.shape:
            return int(hcg.mesh.shape[self.axis_name])
        return len(self.ranks) if self.ranks else 1

    def __repr__(self):
        return f"Group(axis={self.axis_name})"


_GROUPS = {}
_GROUP_COUNTER = [0]


def new_group(ranks=None, backend=None, axis_name=None):
    _GROUP_COUNTER[0] += 1
    g = Group(axis_name or "dp", ranks, id=_GROUP_COUNTER[0])
    _GROUPS[g.id] = g
    return g


def _axis(group) -> Optional[str]:
    if group is None:
        return "dp"
    if isinstance(group, Group):
        return group.axis_name
    if isinstance(group, str):
        return group
    return "dp"


def _in_spmd_trace(arr) -> bool:
    return isinstance(arr, jax.core.Tracer)


def _multi_controller() -> bool:
    return jax.process_count() > 1


import functools as _functools


@_functools.lru_cache(maxsize=None)
def _cross_process_plumbing(devs, ndim):
    """Cached (input sharding, jitted replicate fn) per (device set, rank)
    so eager collectives don't recompile every call."""
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(devs), ("procs",))
    sharding = NamedSharding(mesh, P("procs", *([None] * ndim)))
    rep = NamedSharding(mesh, P(*([None] * (ndim + 1))))
    return sharding, jax.jit(lambda x: x, out_shardings=rep)


def _cross_process(arr, kind, op=ReduceOp.SUM, src=0):
    """Eager collective in multi-controller mode (one process per host as
    set up by `init_parallel_env`/`jax.distributed.initialize`).

    The per-process value is placed as this process's shard of a global
    array over a mesh of all devices, and the collective runs as one XLA
    computation — the TPU-native replacement for the reference's eager
    NCCL calls (`imperative/all_reduce.cc`).  Ranks are processes; with
    multiple local devices per process each device carries the process
    value and the reduction is renormalized.  Returns a host ndarray
    (replicated result) or, for all_gather, the stacked [nranks, ...]
    array ordered by rank.
    """
    import numpy as np

    arr = np.asarray(arr)
    nloc = jax.local_device_count()
    devs = tuple(jax.devices())  # also the mesh order of the sharding
    sharding, replicate = _cross_process_plumbing(devs, arr.ndim)
    d = len(devs)
    local = np.repeat(arr[None], nloc, axis=0)
    ga = jax.make_array_from_process_local_data(
        sharding, local, (d,) + arr.shape)
    gathered = replicate(ga)
    stacked = np.asarray(gathered.addressable_data(0))  # [d, ...]
    # One row per process, ordered by rank.  Rows are selected by each
    # device's process_index rather than assuming jax.devices() is
    # contiguous/ordered by process (JAX doesn't guarantee that on all
    # topologies); first local device of each process carries its value.
    first_row = {}
    for row, dev in enumerate(devs):
        first_row.setdefault(dev.process_index, row)
    per_proc = stacked[[first_row[p] for p in sorted(first_row)]]
    if kind == "all_gather":
        return per_proc
    if kind == "broadcast":
        return per_proc[src]
    if kind == "all_reduce":
        if op == ReduceOp.SUM:
            return per_proc.sum(0)
        if op == ReduceOp.MAX:
            return per_proc.max(0)
        if op == ReduceOp.MIN:
            return per_proc.min(0)
        if op == ReduceOp.AVG:
            return per_proc.mean(0)
        if op == ReduceOp.PROD:
            return per_proc.prod(0)
        raise ValueError(op)
    raise ValueError(kind)


def _axis_in_scope(name) -> bool:
    try:
        lax.axis_size(name)
        return True
    except Exception:
        return False


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """reference collective.py:346 / c_allreduce_sum op."""
    name = _axis(group)
    arr = unwrap(tensor)
    if _in_spmd_trace(arr) and _axis_in_scope(name):
        def f(a):
            if op == ReduceOp.SUM:
                return lax.psum(a, name)
            if op == ReduceOp.MAX:
                return lax.pmax(a, name)
            if op == ReduceOp.MIN:
                return lax.pmin(a, name)
            if op == ReduceOp.AVG:
                return lax.pmean(a, name)
            if op == ReduceOp.PROD:
                return jnp.exp(lax.psum(jnp.log(a), name))
            raise ValueError(op)

        out = dispatch(f, tensor)
        if isinstance(tensor, Tensor):
            tensor.set_value(out._array) if not _in_spmd_trace(out._array) else None
        return out
    if not _in_spmd_trace(arr) and _multi_controller():
        res = _cross_process(arr, "all_reduce", op=op)
        out = Tensor(jnp.asarray(res))
        if isinstance(tensor, Tensor):
            tensor.set_value(out._array)
        return out
    # eager single-controller: replicated value — allreduce is identity
    return tensor


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    name = _axis(group)
    arr = unwrap(tensor)
    if _in_spmd_trace(arr) and _axis_in_scope(name):
        out = dispatch(lambda a: lax.all_gather(a, name), tensor)
        if tensor_list is not None:
            n = lax.axis_size(name)
            from ..ops import unbind

            parts = unbind(out, 0)
            tensor_list.extend(parts)
        return out
    if not _in_spmd_trace(arr) and _multi_controller():
        stacked = _cross_process(arr, "all_gather")
        out = Tensor(jnp.asarray(stacked))
        if tensor_list is not None:
            tensor_list.extend(Tensor(jnp.asarray(s)) for s in stacked)
        return out
    if tensor_list is not None:
        tensor_list.append(tensor)
    return tensor


def all_gather_object(object_list, obj, group=None):
    object_list.append(obj)


def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    name = _axis(group)
    arr = unwrap(tensor if tensor_list is None else tensor_list[0])
    if _in_spmd_trace(arr) and _axis_in_scope(name):
        src = tensor if tensor_list is None else tensor_list
        if isinstance(src, (list, tuple)):
            from ..ops import concat

            src = concat(list(src), axis=0)
        return dispatch(
            lambda a: lax.psum_scatter(a, name, scatter_dimension=0, tiled=True),
            src,
        )
    return tensor


def broadcast(tensor, src=0, group=None, sync_op=True):
    # replicated in single-controller mode; under shard_map use the value of
    # rank `src` along the axis
    name = _axis(group)
    arr = unwrap(tensor)
    if _in_spmd_trace(arr) and _axis_in_scope(name):
        n = lax.axis_size(name)

        def f(a):
            idx = lax.axis_index(name)
            # all-gather then select src slice: XLA lowers to a broadcast
            g = lax.all_gather(a, name)
            return g[src]

        out = dispatch(f, tensor)
        return out
    if not _in_spmd_trace(arr) and _multi_controller():
        res = _cross_process(arr, "broadcast", src=src)
        out = Tensor(jnp.asarray(res))
        if isinstance(tensor, Tensor):
            tensor.set_value(out._array)
        return out
    return tensor


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op, group, sync_op)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    name = _axis(group)
    if tensor_list:
        arr = unwrap(tensor_list[0])
        if _in_spmd_trace(arr) and _axis_in_scope(name):
            from ..ops import stack

            stacked = stack(list(tensor_list), axis=0)

            def f(a):
                return a[lax.axis_index(name)]

            return dispatch(f, stacked)
        return tensor_list[0]
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    """reference alltoall op (`operators/collective/alltoall_op.cu.cc`)."""
    name = _axis(group)
    if isinstance(in_tensor_list, (list, tuple)):
        from ..ops import concat

        x = concat(list(in_tensor_list), axis=0)
    else:
        x = in_tensor_list
    arr = unwrap(x)
    if _in_spmd_trace(arr) and _axis_in_scope(name):
        n = lax.axis_size(name)

        def f(a):
            parts = a.reshape((n, a.shape[0] // n) + a.shape[1:])
            return lax.all_to_all(parts, name, split_axis=0, concat_axis=0,
                                  tiled=False).reshape(a.shape)

        out = dispatch(f, x)
        if out_tensor_list is not None:
            from ..ops import split as _split

            out_tensor_list.extend(_split(out, n, axis=0))
        return out
    if out_tensor_list is not None and isinstance(in_tensor_list, (list, tuple)):
        out_tensor_list.extend(in_tensor_list)
    return x


def send(tensor, dst=0, group=None, sync_op=True):
    # point-to-point maps to ppermute under shard_map (see fleet pipeline);
    # eager mode is single-controller so send/recv are no-ops
    return tensor


def recv(tensor, src=0, group=None, sync_op=True):
    return tensor


def isend(tensor, dst=0, group=None):
    return send(tensor, dst, group)


def irecv(tensor, src=0, group=None):
    return recv(tensor, src, group)


def barrier(group=None):
    if _multi_controller():
        _cross_process(jnp.zeros(()), "all_reduce", op=ReduceOp.SUM)
        return
    (jax.device_put(jnp.zeros(())) + 0).block_until_ready()


def wait(tensor, group=None, use_calc_stream=True):
    arr = unwrap(tensor)
    if hasattr(arr, "block_until_ready"):
        arr.block_until_ready()
    return tensor


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """reference `paddle.distributed.split` (collective.py:1282) — megatron
    style sharded fc/embedding, delegated to fleet mp_layers under the
    current mesh.  Like the reference, each call BUILDS the parallel layer
    (fresh parameters) and applies it — it is a network-construction API,
    not a stateless op.  The constructed layer is exposed as
    ``split.last_layer`` so callers can reach its parameters."""
    from .fleet.meta_parallel import (ColumnParallelLinear,
                                      RowParallelLinear,
                                      VocabParallelEmbedding)
    from .topology import get_hybrid_communicate_group

    # the layers shard over the mesh's 'mp' axis, so num_partitions must
    # agree with it (the reference asserts num_partitions == mp world size,
    # collective.py:1282); validate divisibility against the REAL degree
    hcg = get_hybrid_communicate_group()
    mesh = getattr(hcg, "mesh", None) if hcg is not None else None
    mp_deg = int(mesh.shape.get("mp", 1)) if mesh is not None else None
    if mp_deg is not None and mp_deg > 1 and num_partitions != mp_deg:
        raise ValueError(
            f"num_partitions {num_partitions} must equal the mesh "
            f"mp degree {mp_deg}")
    shards = mp_deg if mp_deg and mp_deg > 1 else max(num_partitions, 1)

    if operation == "linear":
        in_f, out_f = size
        if axis == 0:
            # weight split along rows (in_features): reference row-parallel
            if in_f % shards:
                raise ValueError(
                    f"in_features {in_f} not divisible by {shards} "
                    "partitions")
            layer = RowParallelLinear(in_f, out_f, weight_attr=weight_attr,
                                      has_bias=bias_attr is not False)
        elif axis == 1:
            if out_f % shards:
                raise ValueError(
                    f"out_features {out_f} not divisible by {shards} "
                    "partitions")
            layer = ColumnParallelLinear(in_f, out_f,
                                         weight_attr=weight_attr,
                                         has_bias=bias_attr is not False,
                                         gather_output=gather_out)
        else:
            raise ValueError("linear split axis must be 0 or 1")
    elif operation == "embedding":
        if axis != 0:
            raise ValueError("embedding split supports axis=0 (vocab)")
        vocab, hidden = size
        layer = VocabParallelEmbedding(vocab, hidden,
                                       weight_attr=weight_attr)
    else:
        raise ValueError(
            f"unknown split operation {operation!r}; expected 'linear' or "
            "'embedding'")
    out = layer(x)
    split.last_layer = layer
    return out
