"""Distributed launch CLI (`fleetrun` equivalent).

Reference: `python/paddle/distributed/fleet/launch.py:328,396` — detects
collective vs parameter-server mode, builds the cluster/pod/trainer model
from env or args (`launch_utils.py:59-268`), spawns one process per device
with `PADDLE_TRAINER_ID`/`PADDLE_CURRENT_ENDPOINT`/... env, and watchdogs
the children (`launch_utils.py TrainerProc`: abort all on any failure).

Usage:
    python -m paddle_tpu.distributed.launch [--nproc_per_node N]
        [--servers ip:port,...] [--workers ip:port,...]
        [--log_dir dir] script.py [script args...]

TPU note: one host process per chip-group; JAX process env
(`JAX_PROCESS_COUNT` etc.) rides alongside the PADDLE_* variables so both
API families see the same topology.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

__all__ = ["launch", "main", "get_cluster_from_args"]


def get_cluster_from_args(args) -> dict:
    """Build the {trainers, servers} endpoint model (reference
    `launch_utils.get_cluster_from_args`)."""
    ips = (args.ips or "127.0.0.1").split(",")
    n = args.nproc_per_node
    base = args.start_port
    trainers = [f"{ip}:{base + i}" for ip in ips for i in range(n)]
    servers = [e for e in (args.servers or "").split(",") if e]
    workers = [e for e in (args.workers or "").split(",") if e]
    return {"trainers": trainers, "servers": servers, "workers": workers}


class _Proc:
    def __init__(self, popen, rank, log_fn, log_f):
        self.proc = popen
        self.rank = rank
        self.log_fn = log_fn
        self.log_f = log_f


def _spawn(cmd: List[str], env: dict, log_dir: Optional[str], tag: str,
           rank: int) -> _Proc:
    log_fn, log_f = None, None
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        log_fn = os.path.join(log_dir, f"{tag}.{rank}.log")
        log_f = open(log_fn, "w")
    p = subprocess.Popen(cmd, env=env, stdout=log_f or None,
                         stderr=subprocess.STDOUT if log_f else None)
    return _Proc(p, rank, log_fn, log_f)


def launch(training_script: str, training_script_args: List[str],
           nproc_per_node: int = 1, servers: str = "", workers: str = "",
           ips: str = "127.0.0.1", start_port: int = 6070,
           log_dir: Optional[str] = None, env_extra: Optional[dict] = None,
           node_rank: Optional[int] = None):
    """Programmatic entry (reference `launch.launch_collective/_ps`).
    Returns the list of exit codes for the processes spawned ON THIS NODE:
    with a multi-node `ips` list, each node runs this same command and
    spawns only its own slice of ranks (`node_rank` defaults from
    PADDLE_NODE_RANK, mirroring the reference's pod-by-current-ip filter).
    """
    ns = argparse.Namespace(nproc_per_node=nproc_per_node, servers=servers,
                            workers=workers, ips=ips, start_port=start_port)
    cluster = get_cluster_from_args(ns)
    procs: List[_Proc] = []
    ps_mode = bool(cluster["servers"])
    if node_rank is None:
        node_rank = int(os.environ.get("PADDLE_NODE_RANK", "0"))
    node_ips = (ips or "127.0.0.1").split(",")
    if not 0 <= node_rank < len(node_ips):
        raise ValueError(f"node_rank {node_rank} out of range for ips {ips}")
    node_ip = node_ips[node_rank]

    def _is_local(endpoint: str) -> bool:
        return endpoint.rsplit(":", 1)[0] == node_ip

    def base_env():
        e = dict(os.environ)
        e.update(env_extra or {})
        return e

    try:
        if ps_mode:
            # parameter-server mode: spawn this node's servers then workers
            for i, ep in enumerate(cluster["servers"]):
                if not _is_local(ep):
                    continue
                env = base_env()
                env.update({
                    "TRAINING_ROLE": "PSERVER",
                    "PADDLE_PORT": ep.rsplit(":", 1)[1],
                    "PADDLE_CURRENT_ENDPOINT": ep,
                    "PADDLE_PSERVERS_IP_PORT_LIST": ",".join(
                        cluster["servers"]),
                    "PADDLE_TRAINERS_NUM": str(
                        len(cluster["workers"]) or nproc_per_node),
                })
                procs.append(_spawn(
                    [sys.executable, "-u", training_script,
                     *training_script_args], env, log_dir, "serverlog", i))
            worker_eps = cluster["workers"] or [
                f"127.0.0.1:{start_port + 1000 + i}"
                for i in range(nproc_per_node)]
            n_workers = len(worker_eps)
            for i, wep in enumerate(worker_eps):
                if not _is_local(wep):
                    continue
                env = base_env()
                env.update({
                    "TRAINING_ROLE": "TRAINER",
                    "PADDLE_TRAINER_ID": str(i),
                    "PADDLE_TRAINERS_NUM": str(n_workers),
                    "PADDLE_CURRENT_ENDPOINT": wep,
                    "PADDLE_TRAINER_ENDPOINTS": ",".join(worker_eps),
                    "PADDLE_PSERVERS_IP_PORT_LIST": ",".join(
                        cluster["servers"]),
                })
                procs.append(_spawn(
                    [sys.executable, "-u", training_script,
                     *training_script_args], env, log_dir, "workerlog", i))
        else:
            eps = cluster["trainers"]
            for i, ep in enumerate(eps):
                if not _is_local(ep):
                    continue
                env = base_env()
                env.update({
                    "TRAINING_ROLE": "TRAINER",
                    "PADDLE_TRAINER_ID": str(i),
                    "PADDLE_TRAINERS_NUM": str(len(eps)),
                    "PADDLE_CURRENT_ENDPOINT": ep,
                    "PADDLE_TRAINER_ENDPOINTS": ",".join(eps),
                    "FLAGS_selected_tpus": str(i),
                })
                procs.append(_spawn(
                    [sys.executable, "-u", training_script,
                     *training_script_args], env, log_dir, "workerlog", i))

        if not procs:
            raise RuntimeError(
                f"no server/worker/trainer endpoint matched this node's ip "
                f"{node_ip!r} (node_rank {node_rank} of ips {ips!r}); "
                "check --ips/--node_rank against your endpoint lists")
        # watchdog: any child failing aborts the job (reference
        # `watch_local_trainers` / TrainerProc handling)
        codes = [None] * len(procs)
        while any(c is None for c in codes):
            time.sleep(0.2)
            for idx, p in enumerate(procs):
                if codes[idx] is None:
                    rc = p.proc.poll()
                    if rc is not None:
                        codes[idx] = rc
                        if rc != 0:
                            for q in procs:
                                if q.proc.poll() is None:
                                    q.proc.send_signal(signal.SIGTERM)
        return codes
    finally:
        for p in procs:
            if p.proc.poll() is None:
                p.proc.kill()
            if p.log_f:
                p.log_f.close()


def main(argv=None):
    parser = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    parser.add_argument("--nproc_per_node", type=int,
                        default=int(os.environ.get("PADDLE_NPROC", "1")))
    parser.add_argument("--ips", type=str, default="127.0.0.1")
    parser.add_argument("--servers", type=str, default="")
    parser.add_argument("--workers", type=str, default="")
    parser.add_argument("--start_port", type=int, default=6070)
    parser.add_argument("--node_rank", type=int, default=None)
    parser.add_argument("--log_dir", type=str, default=None)
    parser.add_argument("training_script", type=str)
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    codes = launch(args.training_script, args.training_script_args,
                   nproc_per_node=args.nproc_per_node, servers=args.servers,
                   workers=args.workers, ips=args.ips,
                   start_port=args.start_port, log_dir=args.log_dir,
                   node_rank=args.node_rank)
    bad = [c for c in codes if c]
    sys.exit(bad[0] if bad else 0)


if __name__ == "__main__":
    main()
