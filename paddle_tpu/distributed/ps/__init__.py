"""Parameter-server runtime (Python surface over the native core).

Reference: `python/paddle/distributed/fleet/runtime/the_one_ps.py:434`
(TheOnePSRuntime builds brpc servers/clients from the strategy),
`distributed/service/communicator.h:197` (async Communicator with merge
queues), GEO tables (`distributed/table/sparse_geo_table.cc`).

TPU-native: the server core is csrc/ps_server.cc (TCP + host-memory
tables + server-side optimizers); trainers keep dense compute on TPU and
exchange numpy views at the host boundary.  Three sync modes, matching the
reference's a_sync strategy matrix:

- sync  — push grads / barrier / pull each step
- async — background Communicator thread merges grads and pushes on an
          interval, pulls fresh params (reference Communicator queues)
- geo   — trainers train locally; every k steps push param *deltas*
          (server applies +=) and pull the merged params (GEO-SGD)
"""
from __future__ import annotations

import ctypes
import os
import queue
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ...core import native

OP_PULL_DENSE = 1
OP_PUSH_DENSE_GRAD = 2
OP_SET_DENSE = 3
OP_PULL_SPARSE = 4
OP_PUSH_SPARSE_GRAD = 5
OP_BARRIER = 6
OP_STOP = 7
OP_PUSH_DENSE_DELTA = 8
OP_SAVE_TABLES = 9
OP_GRAPH_ADD_EDGES = 10
OP_GRAPH_SAMPLE_NEIGHBORS = 11
OP_GRAPH_SET_NODE_FEAT = 12
OP_GRAPH_GET_NODE_FEAT = 13

_PS_SIGS = False


def _lib():
    lib = native._load()
    if lib is None:
        raise RuntimeError("native runtime unavailable (PS needs csrc build)")
    global _PS_SIGS
    if not _PS_SIGS:
        lib.ptrt_ps_server_create.restype = ctypes.c_void_p
        lib.ptrt_ps_server_start.restype = ctypes.c_int
        lib.ptrt_ps_server_start.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                             ctypes.c_int, ctypes.c_char_p]
        lib.ptrt_ps_server_create_dense_table.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint64,
            ctypes.c_float, ctypes.c_int]
        lib.ptrt_ps_server_create_sparse_table.restype = ctypes.c_int
        lib.ptrt_ps_server_create_sparse_table.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint64,
            ctypes.c_float, ctypes.c_int]
        lib.ptrt_ps_server_create_sparse_table_ssd.restype = ctypes.c_int
        lib.ptrt_ps_server_create_sparse_table_ssd.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint64,
            ctypes.c_float, ctypes.c_int, ctypes.c_uint64, ctypes.c_char_p]
        lib.ptrt_ps_server_create_graph_table.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint64]
        lib.ptrt_ps_server_save.restype = ctypes.c_int
        lib.ptrt_ps_server_save.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ptrt_ps_server_load.restype = ctypes.c_int
        lib.ptrt_ps_server_load.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ptrt_ps_server_stop.argtypes = [ctypes.c_void_p]
        lib.ptrt_ps_server_stopped.restype = ctypes.c_int
        lib.ptrt_ps_server_stopped.argtypes = [ctypes.c_void_p]
        lib.ptrt_ps_server_destroy.argtypes = [ctypes.c_void_p]
        lib.ptrt_ps_client_create.restype = ctypes.c_void_p
        lib.ptrt_ps_client_connect.restype = ctypes.c_int
        lib.ptrt_ps_client_connect.argtypes = [ctypes.c_void_p,
                                               ctypes.c_char_p, ctypes.c_int]
        lib.ptrt_ps_client_request.restype = ctypes.c_int
        lib.ptrt_ps_client_request.argtypes = [
            ctypes.c_void_p, ctypes.c_uint8, ctypes.c_uint32,
            ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64,
            ctypes.c_void_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64)]
        lib.ptrt_ps_client_destroy.argtypes = [ctypes.c_void_p]
        _PS_SIGS = True
    return lib


class PSServer:
    """In-process native parameter server (reference BrpcPsServer)."""

    # wire codes differ per table kind — use the string names in Python
    DENSE_OPTS = {"sgd": 0, "adagrad": 1, "sum": 2, "adam": 3}
    SPARSE_OPTS = {"sgd": 0, "adagrad": 1, "adam": 2}

    def __init__(self):
        self._lib = _lib()
        self._h = self._lib.ptrt_ps_server_create()
        self.port = None
        self.stopped = False

    def create_dense_table(self, table_id, size, lr=0.01, optimizer="sgd"):
        self._lib.ptrt_ps_server_create_dense_table(
            self._h, table_id, int(size), float(lr),
            self.DENSE_OPTS[optimizer])

    def create_sparse_table(self, table_id, dim, lr=0.01, optimizer="sgd"):
        rc = self._lib.ptrt_ps_server_create_sparse_table(
            self._h, table_id, int(dim), float(lr),
            self.SPARSE_OPTS[optimizer])
        if rc != 0:
            raise ValueError(f"invalid sparse optimizer {optimizer!r}")

    def create_sparse_table_ssd(self, table_id, dim, mem_budget_rows,
                                spill_path, lr=0.01, optimizer="sgd"):
        """SSD-spillable sparse table (reference
        `distributed/table/ssd_sparse_table.cc`): at most
        ``mem_budget_rows`` rows stay in host memory; the LRU overflow
        (param + optimizer slots) lives in the slotted ``spill_path``
        file.  save()/load() snapshots fold spilled rows in, so tables
        larger than the budget survive a restart."""
        rc = self._lib.ptrt_ps_server_create_sparse_table_ssd(
            self._h, table_id, int(dim), float(lr),
            self.SPARSE_OPTS[optimizer], int(mem_budget_rows),
            str(spill_path).encode())
        if rc != 0:
            raise ValueError(
                f"create_sparse_table_ssd failed (optimizer {optimizer!r}, "
                f"path {spill_path!r})")

    def create_graph_table(self, table_id, feat_dim=0):
        """Graph table (reference
        `distributed/table/common_graph_table.cc`): weighted adjacency +
        per-node features, served over the PS transport for GNN
        neighbor sampling (`graph_brpc_server.cc`)."""
        self._lib.ptrt_ps_server_create_graph_table(self._h, table_id,
                                                    int(feat_dim))

    def start(self, port=0, n_trainers=1, host="127.0.0.1"):
        """Bind defaults to loopback — the wire protocol is unauthenticated
        (same trust model as the reference's brpc PS); pass host="0.0.0.0"
        explicitly for a trusted multi-host network."""
        self.port = self._lib.ptrt_ps_server_start(self._h, int(port),
                                                   int(n_trainers),
                                                   host.encode())
        if self.port < 0:
            raise RuntimeError(f"PS server failed to bind {host}:{port}")
        return self.port

    def save(self, path: str) -> None:
        """Persist all tables + optimizer slots (reference
        _save_distributed_persistables)."""
        if self._lib.ptrt_ps_server_save(self._h, path.encode()) != 0:
            raise RuntimeError(f"PS server save to {path} failed")

    def load(self, path: str) -> None:
        """Restore tables saved by save(); call before start()."""
        if self._lib.ptrt_ps_server_load(self._h, path.encode()) != 0:
            raise RuntimeError(f"PS server load from {path} failed")

    def stop(self):
        if self._h:
            self._lib.ptrt_ps_server_stop(self._h)
        self.stopped = True

    def is_stopped(self) -> bool:
        """True once the native server saw a stop — locally via stop() or
        remotely via a client OP_STOP."""
        if self.stopped:
            return True
        return bool(self._h and self._lib.ptrt_ps_server_stopped(self._h))

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.ptrt_ps_server_stop(self._h)
                self._lib.ptrt_ps_server_destroy(self._h)
                self._h = None
        except Exception:
            pass


class PSClient:
    """Trainer-side connection (reference BrpcPsClient)."""

    def __init__(self, host="127.0.0.1", port=0):
        self._lib = _lib()
        self._h = self._lib.ptrt_ps_client_create()
        rc = self._lib.ptrt_ps_client_connect(self._h, host.encode(),
                                              int(port))
        if rc != 0:
            raise ConnectionError(f"cannot connect PS at {host}:{port}")

    def _request(self, op, table, n, payload: bytes, out_cap: int) -> bytes:
        out = ctypes.create_string_buffer(out_cap) if out_cap else None
        out_len = ctypes.c_uint64(0)
        rc = self._lib.ptrt_ps_client_request(
            self._h, op, table, n, payload, len(payload) if payload else 0,
            out, out_cap, ctypes.byref(out_len))
        if rc != 0:
            raise RuntimeError(f"PS request op={op} failed rc={rc}")
        return out.raw[: out_len.value] if out else b""

    def pull_dense(self, table, size) -> np.ndarray:
        raw = self._request(OP_PULL_DENSE, table, size, b"",
                            size * 4 + 16)
        return np.frombuffer(raw, np.float32, count=size).copy()

    def push_dense_grad(self, table, grad: np.ndarray):
        g = np.ascontiguousarray(grad, np.float32)
        self._request(OP_PUSH_DENSE_GRAD, table, g.size, g.tobytes(), 0)

    def push_dense_delta(self, table, delta: np.ndarray):
        d = np.ascontiguousarray(delta, np.float32)
        self._request(OP_PUSH_DENSE_DELTA, table, d.size, d.tobytes(), 0)

    def set_dense(self, table, value: np.ndarray):
        v = np.ascontiguousarray(value, np.float32)
        self._request(OP_SET_DENSE, table, v.size, v.tobytes(), 0)

    def pull_sparse(self, table, ids: np.ndarray, dim: int) -> np.ndarray:
        ids = np.ascontiguousarray(ids, np.uint64)
        raw = self._request(OP_PULL_SPARSE, table, ids.size, ids.tobytes(),
                            ids.size * dim * 4 + 16)
        return np.frombuffer(raw, np.float32,
                             count=ids.size * dim).reshape(ids.size, dim).copy()

    def push_sparse_grad(self, table, ids: np.ndarray, grads: np.ndarray):
        ids = np.ascontiguousarray(ids, np.uint64)
        g = np.ascontiguousarray(grads, np.float32)
        self._request(OP_PUSH_SPARSE_GRAD, table, ids.size,
                      ids.tobytes() + g.tobytes(), 0)

    # -- graph service (reference graph_brpc_client.cc) ---------------------
    def add_graph_edges(self, table, src: np.ndarray, dst: np.ndarray,
                        weight: Optional[np.ndarray] = None):
        src = np.ascontiguousarray(src, np.uint64)
        dst = np.ascontiguousarray(dst, np.uint64)
        w = (np.ascontiguousarray(weight, np.float32) if weight is not None
             else np.ones(src.size, np.float32))
        rec = np.zeros(src.size, dtype=[("s", "<u8"), ("d", "<u8"),
                                        ("w", "<f4")])
        rec["s"], rec["d"], rec["w"] = src, dst, w
        self._request(OP_GRAPH_ADD_EDGES, table, src.size, rec.tobytes(), 0)

    def sample_neighbors(self, table, ids: np.ndarray, sample_size: int,
                         seed: int = 0):
        """Weighted neighbor sampling without replacement
        (Efraimidis-Spirakis keys from a deterministic splitmix hash —
        replayable in numpy, see tests).  Returns (neighbors
        [n, sample_size] uint64 0-padded, counts [n] int32)."""
        ids = np.ascontiguousarray(ids, np.uint64)
        k = int(sample_size)
        payload = (np.uint32(k).tobytes() + np.uint32(seed).tobytes() +
                   ids.tobytes())
        rec_bytes = 4 + k * 8
        raw = self._request(OP_GRAPH_SAMPLE_NEIGHBORS, table, ids.size,
                            payload, ids.size * rec_bytes + 16)
        rec = np.frombuffer(raw, np.uint8,
                            count=ids.size * rec_bytes).reshape(
                                ids.size, rec_bytes)
        counts = rec[:, :4].copy().view(np.int32).reshape(-1)
        nbrs = rec[:, 4:].copy().view(np.uint64).reshape(ids.size, k)
        return nbrs, counts

    def set_node_feat(self, table, ids: np.ndarray, feats: np.ndarray):
        ids = np.ascontiguousarray(ids, np.uint64)
        f = np.ascontiguousarray(feats, np.float32).reshape(ids.size, -1)
        rec = ids.reshape(-1, 1).view(np.uint8).reshape(ids.size, 8)
        payload = np.concatenate(
            [rec, f.view(np.uint8).reshape(ids.size, -1)],
            axis=1).tobytes()
        self._request(OP_GRAPH_SET_NODE_FEAT, table, ids.size, payload, 0)

    def get_node_feat(self, table, ids: np.ndarray, dim: int) -> np.ndarray:
        ids = np.ascontiguousarray(ids, np.uint64)
        raw = self._request(OP_GRAPH_GET_NODE_FEAT, table, ids.size,
                            ids.tobytes(), ids.size * dim * 4 + 16)
        return np.frombuffer(raw, np.float32,
                             count=ids.size * dim).reshape(
                                 ids.size, dim).copy()

    def barrier(self, trainer_id=None, table=0):
        """Block until all n_trainers distinct trainer ids arrive (restarts
        of the same id don't double-count).  trainer_id defaults from
        PADDLE_TRAINER_ID so distinct launched workers stay distinct."""
        if trainer_id is None:
            trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._request(OP_BARRIER, table, int(trainer_id), b"", 0)

    def save_tables(self, path: str):
        """Ask the server to persist its tables to `path` (server-host
        filesystem)."""
        self._request(OP_SAVE_TABLES, 0, 0, path.encode(), 0)

    def stop_server(self):
        try:
            self._request(OP_STOP, 0, 0, b"", 0)
        except RuntimeError:
            pass

    def close(self):
        if getattr(self, "_h", None):
            self._lib.ptrt_ps_client_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def shard_dense_sizes(total: int, n_shards: int) -> List[int]:
    """Contiguous block partition of a dense param across servers
    (reference common table block scheme: even blocks, remainder spread
    over the leading shards)."""
    base, rem = divmod(int(total), n_shards)
    return [base + (1 if i < rem else 0) for i in range(n_shards)]


class ShardedPSClient:
    """Client-side routing over multiple PS servers (reference
    `brpc_ps_client.cc` request fan-out + `common_sparse_table.cc` block
    partitioning).

    - dense tables are split into contiguous blocks, one block per server
      (`shard_dense_sizes`); pull concatenates, push scatters.
    - sparse rows route by ``id % n_servers`` (the reference's
      shard-by-modulo), so each server owns a disjoint id set.
    Servers must be created with the per-shard sizes, e.g. via
    ``create_dense_table(tid, shard_dense_sizes(total, n)[i])`` on server i.
    """

    def __init__(self, endpoints: List):
        """endpoints: list of (host, port) or "host:port" strings."""
        from concurrent.futures import ThreadPoolExecutor

        self.clients: List[PSClient] = []
        for ep in endpoints:
            if isinstance(ep, str):
                host, port = ep.rsplit(":", 1)
            else:
                host, port = ep
            self.clients.append(PSClient(host, int(port)))
        self.n = len(self.clients)
        self._dense_sizes: Dict[int, List[int]] = {}
        # per-shard requests go out concurrently (reference brpc fan-out);
        # each PSClient serializes its own socket internally
        self._pool = ThreadPoolExecutor(max_workers=self.n,
                                        thread_name_prefix="ps-shard")

    def _fanout(self, calls):
        """Run [(fn, args...)] concurrently, return results in order."""
        futs = [self._pool.submit(fn, *args) for fn, *args in calls]
        return [f.result() for f in futs]

    def register_dense(self, table_id, total_size):
        self._dense_sizes[table_id] = shard_dense_sizes(total_size, self.n)

    def _splits(self, table_id, total=None):
        sizes = self._dense_sizes.get(table_id)
        if sizes is None:
            if total is None:
                raise KeyError(f"dense table {table_id} not registered")
            sizes = shard_dense_sizes(total, self.n)
            self._dense_sizes[table_id] = sizes
        return sizes

    # -- dense ---------------------------------------------------------------
    def pull_dense(self, table, size) -> np.ndarray:
        sizes = self._splits(table, size)
        parts = self._fanout([(c.pull_dense, table, s)
                              for c, s in zip(self.clients, sizes) if s])
        return np.concatenate(parts) if parts else np.zeros(0, np.float32)

    def _scatter_dense(self, table, arr, fn_name):
        arr = np.ascontiguousarray(arr, np.float32).reshape(-1)
        sizes = self._splits(table, arr.size)
        calls, off = [], 0
        for c, s in zip(self.clients, sizes):
            if s:
                calls.append((getattr(c, fn_name), table, arr[off:off + s]))
            off += s
        self._fanout(calls)

    def push_dense_grad(self, table, grad):
        self._scatter_dense(table, grad, "push_dense_grad")

    def push_dense_delta(self, table, delta):
        self._scatter_dense(table, delta, "push_dense_delta")

    def set_dense(self, table, value):
        self._scatter_dense(table, value, "set_dense")

    # -- sparse --------------------------------------------------------------
    def pull_sparse(self, table, ids: np.ndarray, dim: int) -> np.ndarray:
        ids = np.ascontiguousarray(ids, np.uint64)
        shard = (ids % np.uint64(self.n)).astype(np.int64)
        out = np.zeros((ids.size, dim), np.float32)
        masks = [shard == s for s in range(self.n)]
        calls = [(self.clients[s].pull_sparse, table, ids[m], dim)
                 for s, m in enumerate(masks) if m.any()]
        results = self._fanout(calls)
        ri = 0
        for m in masks:
            if m.any():
                out[m] = results[ri]
                ri += 1
        return out

    def push_sparse_grad(self, table, ids: np.ndarray, grads: np.ndarray):
        ids = np.ascontiguousarray(ids, np.uint64)
        grads = np.ascontiguousarray(grads, np.float32).reshape(ids.size, -1)
        shard = (ids % np.uint64(self.n)).astype(np.int64)
        self._fanout([
            (self.clients[s].push_sparse_grad, table, ids[shard == s],
             grads[shard == s])
            for s in range(self.n) if (shard == s).any()
        ])

    # -- control -------------------------------------------------------------
    def barrier(self, trainer_id=None, table=0):
        # one designated server arbitrates the barrier; all trainers use the
        # same fan-out order so server 0 is consistent across the job
        self.clients[0].barrier(trainer_id, table)

    def save_tables(self, path_prefix: str):
        """Each server persists its shard to `{prefix}.shard{i}`."""
        self._fanout([(c.save_tables, f"{path_prefix}.shard{i}")
                      for i, c in enumerate(self.clients)])

    def stop_servers(self):
        for c in self.clients:
            c.stop_server()

    def close(self):
        self._pool.shutdown(wait=False)
        for c in self.clients:
            c.close()


class Communicator:
    """Async gradient communicator (reference
    `distributed/service/communicator.h:197`): trainers enqueue grads;  a
    background thread merges same-table grads and pushes them, then pulls
    fresh params into a cache the trainer reads at its own pace.

    GEO mode (`sparse_geo_table.cc`): `geo_step` marks the table for
    delta-sync every `k_steps` calls instead of per-grad pushes."""

    def __init__(self, client: PSClient, mode="async", send_interval_s=0.01,
                 merge_size=4, k_steps=4):
        self.client = client
        self.mode = mode
        self.send_interval_s = send_interval_s
        self.merge_size = merge_size
        self.k_steps = max(1, int(k_steps))
        self._q: "queue.Queue" = queue.Queue()
        self._params: Dict[int, np.ndarray] = {}
        self._sizes: Dict[int, int] = {}
        self._lock = threading.Lock()
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._send_error: Optional[Exception] = None
        self._geo_old: Dict[int, np.ndarray] = {}
        self._geo_tick: Dict[int, int] = {}

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._running = False
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None

    def is_running(self):
        return self._running

    # -- trainer API --------------------------------------------------------
    def register_dense(self, table_id, size):
        self._sizes[table_id] = int(size)

    def send(self, table_id, grad: np.ndarray):
        """Enqueue a dense grad for async merge+push.  Once the background
        thread has died, every call raises (the error is sticky — the
        thread does not restart, so silently queueing would grow forever)."""
        if self._send_error is not None:
            raise RuntimeError(
                "PS communicator send thread failed; restart the "
                "communicator") from self._send_error
        self._q.put((table_id, np.asarray(grad, np.float32)))

    def recv(self, table_id) -> Optional[np.ndarray]:
        """Latest pulled params (may lag; that's the async contract)."""
        with self._lock:
            p = self._params.get(table_id)
        if p is None:  # first touch: synchronous pull
            p = self.client.pull_dense(table_id, self._sizes[table_id])
            with self._lock:
                self._params[table_id] = p
        return p

    def geo_step(self, table_id, local_param: np.ndarray) -> np.ndarray:
        """GEO-SGD: every k calls push (local - last_synced) as a delta and
        pull the merged global params; returns the params the trainer
        should continue from."""
        local = np.asarray(local_param, np.float32)
        tick = self._geo_tick.get(table_id, 0) + 1
        self._geo_tick[table_id] = tick
        if table_id not in self._geo_old:
            # last-synced state is the SERVER's params (the trainer may
            # already have stepped locally before the first geo_step)
            self._geo_old[table_id] = self.client.pull_dense(
                table_id, local.size).reshape(local.shape)
        if tick % self.k_steps:
            return local
        delta = local - self._geo_old[table_id]
        self.client.push_dense_delta(table_id, delta)
        fresh = self.client.pull_dense(table_id, local.size).reshape(
            local.shape)
        self._geo_old[table_id] = fresh.copy()
        return fresh

    # -- background loop ----------------------------------------------------
    def _loop(self):
        while self._running:
            merged: Dict[int, np.ndarray] = {}
            count = 0
            deadline = time.monotonic() + self.send_interval_s
            while count < self.merge_size and time.monotonic() < deadline:
                try:
                    tid, g = self._q.get(timeout=self.send_interval_s)
                except queue.Empty:
                    break
                merged[tid] = g if tid not in merged else merged[tid] + g
                count += 1
            for tid, g in merged.items():
                try:
                    self.client.push_dense_grad(tid, g)
                    # size falls back to the pushed grad's size so an
                    # unregistered table cannot kill the send thread
                    size = self._sizes.get(tid, g.size)
                    fresh = self.client.pull_dense(tid, size)
                    with self._lock:
                        self._params[tid] = fresh
                except Exception as e:  # noqa: BLE001 — surfaced via send()
                    if self._running:
                        self._send_error = e
                        self._running = False
                    return


# ---------------------------------------------------------------------------
# Heterogeneous trainer service (reference heter_client.cc/heter_server.cc +
# operators/pscore/heter_listen_and_serv_op.cc): a worker offloads part of
# its step — by name — to a peer process holding different hardware (the
# reference's CPU-param / accelerator-dense split).  Transport mirrors the
# PS framing; payloads are named numpy arrays.
# ---------------------------------------------------------------------------
import socket
import struct


# Wire codec for the heter service: a restricted binary encoding of
# (name/status, [numpy arrays]).  Deliberately NOT pickle — the transport
# is unauthenticated (PS trust model: data tampering is in-scope), and
# pickle would escalate that to arbitrary code execution.
def _enc_arrays(tag: str, arrays) -> bytes:
    tb = tag.encode()
    out = [struct.pack("<H", len(tb)), tb,
           struct.pack("<H", len(arrays))]
    for a in arrays:
        a = np.ascontiguousarray(a)
        ds = a.dtype.str.encode()
        out.append(struct.pack("<H", len(ds)))
        out.append(ds)
        out.append(struct.pack("<B", a.ndim))
        out.append(struct.pack(f"<{a.ndim}q", *a.shape) if a.ndim else b"")
        out.append(struct.pack("<Q", a.nbytes))
        out.append(a.tobytes())
    return b"".join(out)


def _dec_arrays(buf: bytes):
    off = 0

    def take(n):
        nonlocal off
        if off + n > len(buf):
            raise ValueError("truncated heter message")
        b = buf[off:off + n]
        off += n
        return b

    (tl,) = struct.unpack("<H", take(2))
    tag = take(tl).decode()
    (cnt,) = struct.unpack("<H", take(2))
    arrays = []
    for _ in range(cnt):
        (dl,) = struct.unpack("<H", take(2))
        dt = np.dtype(take(dl).decode())
        if dt.hasobject:
            raise ValueError("object dtypes are not allowed on the wire")
        (ndim,) = struct.unpack("<B", take(1))
        shape = struct.unpack(f"<{ndim}q", take(8 * ndim)) if ndim else ()
        (nbytes,) = struct.unpack("<Q", take(8))
        arrays.append(np.frombuffer(take(nbytes), dt).reshape(shape).copy())
    return tag, arrays


class HeterServer:
    """Serves registered callables to HeterClients.

    ``register(name, fn)`` exposes ``fn(*arrays) -> array | tuple`` —
    typically a jitted sub-program (the reference's heter "section").
    Loopback bind by default; the protocol is unauthenticated (same trust
    model as the PS transport) but carries only a restricted
    dtype/shape/bytes array encoding — never pickled objects."""

    def __init__(self):
        self._fns: Dict[str, object] = {}
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self.port = None

    def register(self, name: str, fn):
        self._fns[name] = fn

    def start(self, port=0, host="127.0.0.1"):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._running = True
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        self._running = False
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- internals ----------------------------------------------------------
    def _accept_loop(self):
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket):
        try:
            while self._running:
                hdr = self._read_full(conn, 4)
                if hdr is None:
                    return
                (ln,) = struct.unpack("<I", hdr)
                if ln > (1 << 30):
                    return
                body = self._read_full(conn, ln)
                if body is None:
                    return
                try:
                    name, arrays = _dec_arrays(body)
                    fn = self._fns[name]
                    out = fn(*arrays)
                    if not isinstance(out, (list, tuple)):
                        out = (out,)
                    resp = _enc_arrays(
                        "ok", [np.asarray(o) for o in out])
                except Exception as e:  # noqa: BLE001 — shipped to caller
                    resp = _enc_arrays(
                        "err:" + str(e)[:500], [])
                conn.sendall(struct.pack("<I", len(resp)) + resp)
        finally:
            conn.close()

    @staticmethod
    def _read_full(conn, n):
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf


class HeterClient:
    """Worker-side handle: ``run(name, *arrays)`` executes the named
    section on the heter server and returns its output arrays
    (reference HeterClient::SendAndRecvAsync)."""

    def __init__(self, host="127.0.0.1", port=0):
        self._sock = socket.create_connection((host, port))

    def run(self, name: str, *arrays):
        payload = _enc_arrays(name, [np.asarray(a) for a in arrays])
        self._sock.sendall(struct.pack("<I", len(payload)) + payload)
        hdr = HeterServer._read_full(self._sock, 4)
        if hdr is None:
            raise ConnectionError("heter server closed the connection")
        (ln,) = struct.unpack("<I", hdr)
        body = HeterServer._read_full(self._sock, ln)
        if body is None:
            raise ConnectionError(
                "heter server closed mid-response")
        tag, out = _dec_arrays(body)
        if tag != "ok":
            raise RuntimeError(
                f"heter section {name!r} failed: {tag[4:]}")
        return tuple(out) if len(out) != 1 else out[0]

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass
