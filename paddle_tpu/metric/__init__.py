"""Metrics (reference `python/paddle/metric/metrics.py:37-592`)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        idx = np.argsort(-pred_np, axis=-1)[..., : self.maxk]
        if label_np.ndim == pred_np.ndim:
            label_np = label_np.squeeze(-1)
        correct = idx == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = _np(correct)
        num = c.shape[0] if c.ndim > 0 else 1
        accs = []
        for k in self.topk:
            corr_k = c[..., :k].sum()
            accs.append(corr_k / max(num, 1))
            self.total[self.topk.index(k)] += corr_k
            self.count[self.topk.index(k)] += num
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / c if c > 0 else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(np.sum((p == 1) & (l == 1)))
        self.fp += int(np.sum((p == 1) & (l == 0)))

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(np.sum((p == 1) & (l == 1)))
        self.fn += int(np.sum((p == 0) & (l == 1)))

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2:
            p = p[:, 1]
        l = _np(labels).reshape(-1)
        bins = np.minimum((p * self.num_thresholds).astype(np.int64),
                          self.num_thresholds - 1)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds, dtype=np.int64)
        self._stat_neg = np.zeros(self.num_thresholds, dtype=np.int64)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoid over thresholds from high to low
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapz(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    pred = _np(input)
    lab = _np(label)
    idx = np.argsort(-pred, axis=-1)[..., :k]
    if lab.ndim == pred.ndim:
        lab = lab.squeeze(-1)
    corr = (idx == lab[..., None]).any(axis=-1).mean()
    return Tensor(np.asarray(corr, dtype=np.float32))
