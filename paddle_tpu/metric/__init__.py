"""Metrics (reference `python/paddle/metric/metrics.py:37-592`)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        idx = np.argsort(-pred_np, axis=-1)[..., : self.maxk]
        if label_np.ndim == pred_np.ndim:
            label_np = label_np.squeeze(-1)
        correct = idx == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = _np(correct)
        num = c.shape[0] if c.ndim > 0 else 1
        accs = []
        for k in self.topk:
            corr_k = c[..., :k].sum()
            accs.append(corr_k / max(num, 1))
            self.total[self.topk.index(k)] += corr_k
            self.count[self.topk.index(k)] += num
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / c if c > 0 else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(np.sum((p == 1) & (l == 1)))
        self.fp += int(np.sum((p == 1) & (l == 0)))

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(np.sum((p == 1) & (l == 1)))
        self.fn += int(np.sum((p == 0) & (l == 1)))

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2:
            p = p[:, 1]
        l = _np(labels).reshape(-1)
        bins = np.minimum((p * self.num_thresholds).astype(np.int64),
                          self.num_thresholds - 1)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds, dtype=np.int64)
        self._stat_neg = np.zeros(self.num_thresholds, dtype=np.int64)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoid over thresholds from high to low
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapz(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    pred = _np(input)
    lab = _np(label)
    idx = np.argsort(-pred, axis=-1)[..., :k]
    if lab.ndim == pred.ndim:
        lab = lab.squeeze(-1)
    corr = (idx == lab[..., None]).any(axis=-1).mean()
    return Tensor(np.asarray(corr, dtype=np.float32))


def extract_chunk_spans(tags, scheme="IOB", num_chunk_types=1,
                        excluded=()):
    """[(start, end_exclusive, type)] per the reference's GetSegments
    rules (`operators/metrics/chunk_eval_op.h`): label = chunk_type *
    num_tag_types + tag; labels outside [0, num_chunk_types*num_tag_types)
    are Outside.  Schemes: plain (every in-range position its own
    chunk), IOB (B=0/I=1), IOE (I=0/E=1), IOBES (B/I/E/S=0..3).  The
    ONE chunk decoder — ChunkEvaluator and the `chunk_eval` interp
    translator both use it."""
    n_tag = {"plain": 1, "IOB": 2, "IOE": 2, "IOBES": 4}[scheme]
    excluded = set(excluded)
    chunks = []
    start = None
    cur_type = None

    def flush(end):
        if start is not None and cur_type not in excluded:
            chunks.append((start, end, cur_type))

    tags = [int(t) for t in tags]
    for i, lab in enumerate(tags):
        if lab < 0 or lab >= num_chunk_types * n_tag:
            flush(i)
            start, cur_type = None, None
            continue
        ctype, tag = divmod(lab, n_tag)
        if scheme == "plain":
            begins, ends_here = True, True
        elif scheme == "IOB":
            begins = tag == 0 or ctype != cur_type or start is None
            ends_here = False
        elif scheme == "IOE":
            begins = ctype != cur_type or start is None
            ends_here = tag == 1
        else:  # IOBES
            begins = tag in (0, 3) or ctype != cur_type or start is None
            ends_here = tag in (2, 3)
        if begins:
            flush(i)
            start, cur_type = i, ctype
        if ends_here:
            flush(i + 1)
            start, cur_type = None, None
    flush(len(tags))
    return chunks


class ChunkEvaluator(Metric):
    """Chunking (NER) precision/recall/F1 over IOB-style tag sequences
    (reference `operators/metrics/chunk_eval_op.*` + `metric` wrapper).
    update() takes (num_infer_chunks, num_label_chunks, num_correct_chunks)
    like the reference's compute() outputs."""

    def __init__(self, name=None):
        self._name = name or "chunk"
        self.reset()

    def reset(self):
        self.num_infer = 0
        self.num_label = 0
        self.num_correct = 0

    def update(self, num_infer_chunks, num_label_chunks,
               num_correct_chunks):
        def _i(v):
            return int(np.asarray(v.numpy() if hasattr(v, "numpy") else v)
                       .sum())

        self.num_infer += _i(num_infer_chunks)
        self.num_label += _i(num_label_chunks)
        self.num_correct += _i(num_correct_chunks)

    def accumulate(self):
        p = self.num_correct / self.num_infer if self.num_infer else 0.0
        r = self.num_correct / self.num_label if self.num_label else 0.0
        f1 = 2 * p * r / (p + r) if p + r else 0.0
        return p, r, f1

    def name(self):
        return self._name

    @staticmethod
    def extract_chunks(tags, scheme="IOB", n_types=None):
        """Decode (start, end_inclusive, type) chunks; thin wrapper over
        the module-level multi-scheme `extract_chunk_spans` (the single
        chunk decoder, shared with the `chunk_eval` interp translator)."""
        spans = extract_chunk_spans(
            tags, scheme=scheme,
            num_chunk_types=n_types if n_types is not None else 1 << 30,
            excluded=())
        return [(s, e - 1, t) for s, e, t in spans]

    def compute(self, infer_tags, label_tags, lengths=None, n_types=None):
        """Host-side chunk extraction; returns the three counts update()
        wants."""
        inf = np.asarray(infer_tags.numpy() if hasattr(infer_tags, "numpy")
                         else infer_tags)
        lab = np.asarray(label_tags.numpy() if hasattr(label_tags, "numpy")
                         else label_tags)
        if inf.ndim == 1:
            inf, lab = inf[None], lab[None]
        lens = np.asarray(lengths.numpy() if hasattr(lengths, "numpy")
                          else lengths) if lengths is not None else \
            np.full(inf.shape[0], inf.shape[1])
        ni = nl = nc = 0
        for row_i, row_l, L in zip(inf, lab, lens):
            ci = set(self.extract_chunks(row_i[:int(L)], n_types=n_types))
            cl = set(self.extract_chunks(row_l[:int(L)], n_types=n_types))
            ni += len(ci)
            nl += len(cl)
            nc += len(ci & cl)
        return ni, nl, nc


def _voc_ap(rec, prec, ap_type):
    """Average precision per the reference `detection_map_op.h:460-482`:
    '11point' = mean of max-precision at 11 recall thresholds;
    'integral' = the NATURAL integral sum(prec[i] * delta_rec[i]) — NOT
    the VOC-interpolated variant (round-4 fix: both callers previously
    right-maxed the curve, inflating integral AP)."""
    if ap_type == "11point":
        return float(np.mean(
            [prec[rec >= t].max() if (rec >= t).any() else 0.0
             for t in np.linspace(0, 1, 11)]))
    ap = 0.0
    prev = 0.0
    for r, pv in zip(rec, prec):
        if abs(r - prev) > 1e-6:
            ap += float(pv) * abs(float(r) - prev)
        prev = float(r)
    return ap


class DetectionMAP(Metric):
    """VOC-style detection mAP (reference `operators/metrics/` detection
    map machinery + `fluid/metrics.py DetectionMAP`): 11-point or
    'integral' interpolated average precision over IoU-matched
    detections."""

    def __init__(self, overlap_threshold=0.5, ap_version="11point",
                 class_num=None, name=None):
        self.overlap_threshold = overlap_threshold
        self.ap_version = ap_version
        self._name = name or "mAP"
        self.reset()

    def reset(self):
        self._dets = []   # (class, score, matched_gt)
        self._gt_count = {}

    def update(self, pred_boxes, pred_scores, pred_labels, gt_boxes,
               gt_labels):
        """Single-image update; all inputs numpy-able. pred_boxes [P,4],
        gt_boxes [G,4] xyxy."""
        def _np_(v):
            return np.asarray(v.numpy() if hasattr(v, "numpy") else v)

        pb, ps, pl = _np_(pred_boxes), _np_(pred_scores), _np_(pred_labels)
        gb, gl = _np_(gt_boxes), _np_(gt_labels)
        for c in np.unique(gl):
            self._gt_count[int(c)] = self._gt_count.get(int(c), 0) + \
                int((gl == c).sum())
        order = np.argsort(-ps)
        taken = np.zeros(len(gb), bool)
        for i in order:
            c = int(pl[i])
            best, best_j = 0.0, -1
            for j in range(len(gb)):
                if taken[j] or int(gl[j]) != c:
                    continue
                ixmin = max(pb[i, 0], gb[j, 0])
                iymin = max(pb[i, 1], gb[j, 1])
                ixmax = min(pb[i, 2], gb[j, 2])
                iymax = min(pb[i, 3], gb[j, 3])
                iw = max(ixmax - ixmin, 0)
                ih = max(iymax - iymin, 0)
                inter = iw * ih
                a1 = (pb[i, 2] - pb[i, 0]) * (pb[i, 3] - pb[i, 1])
                a2 = (gb[j, 2] - gb[j, 0]) * (gb[j, 3] - gb[j, 1])
                iou = inter / max(a1 + a2 - inter, 1e-10)
                if iou > best:
                    best, best_j = iou, j
            # strict >, matching detection_map_op.h:401
            hit = best > self.overlap_threshold and best_j >= 0
            if hit:
                taken[best_j] = True
            self._dets.append((c, float(ps[i]), hit))

    def accumulate(self):
        aps = []
        for c, total in self._gt_count.items():
            rows = sorted((d for d in self._dets if d[0] == c),
                          key=lambda d: -d[1])
            if not rows or total == 0:
                continue
            tp = np.cumsum([1.0 if r[2] else 0.0 for r in rows])
            fp = np.cumsum([0.0 if r[2] else 1.0 for r in rows])
            rec = tp / total
            prec = tp / np.maximum(tp + fp, 1e-10)
            aps.append(_voc_ap(rec, prec, self.ap_version))
        return float(np.mean(aps)) if aps else 0.0

    def name(self):
        return self._name


def mean_iou(pred, label, num_classes, name=None):
    """Semantic-segmentation mean IoU — delegates to the JAX-native
    confusion-matrix implementation (`ops/misc.py` mean_iou, reference
    `operators/metrics/mean_iou_op.*` return contract)."""
    from ..ops.misc import mean_iou as _mi

    if not hasattr(pred, "numpy"):
        pred = Tensor(np.asarray(pred))
    if not hasattr(label, "numpy"):
        label = Tensor(np.asarray(label))
    return _mi(pred, label, num_classes)


def detection_map_update(det, det_lens, gt, gt_lens, pos_count,
                         true_pos, tp_count, false_pos, fp_count,
                         class_num, overlap_threshold=0.5,
                         ap_type="11point", evaluate_difficult=True):
    """Numpy core of the `detection_map` op
    (`operators/detection/detection_map_op.cc`), shared by the interp
    translator and host evaluators.

    det: [B, M, 6] padded (label, score, x1, y1, x2, y2) with per-image
    det_lens [B]; gt: [B, G, 6] (label, [difficult,] x1, y1, x2, y2 —
    6-wide rows carry `difficult` at col 1) with gt_lens [B].  States
    are FIXED-CAPACITY dense stand-ins for the reference's growing LoD
    tensors: pos_count [C], (true|false)_pos [C, CAP, 2] (score, flag)
    with valid counts (tp|fp)_count [C].  Returns the accumulated
    states + mAP.  Overflow beyond CAP raises (silent drops would skew
    the metric).
    """
    det = np.asarray(det, np.float32)
    gt = np.asarray(gt, np.float32)
    C = int(class_num)
    pos_count = np.array(pos_count, np.int64).reshape(C).copy()
    cap = int(np.shape(true_pos)[1])
    true_pos = np.array(true_pos, np.float32).reshape(C, cap, 2).copy()
    false_pos = np.array(false_pos, np.float32).reshape(C, cap, 2).copy()
    tp_count = np.array(tp_count, np.int64).reshape(C).copy()
    fp_count = np.array(fp_count, np.int64).reshape(C).copy()

    def iou(a, b):
        ix1 = np.maximum(a[0], b[0])
        iy1 = np.maximum(a[1], b[1])
        ix2 = np.minimum(a[2], b[2])
        iy2 = np.minimum(a[3], b[3])
        iw = max(ix2 - ix1, 0.0)
        ih = max(iy2 - iy1, 0.0)
        inter = iw * ih
        ua = ((a[2] - a[0]) * (a[3] - a[1])
              + (b[2] - b[0]) * (b[3] - b[1]) - inter)
        return inter / ua if ua > 0 else 0.0

    def push(buf, cnt, c, score, flag):
        if cnt[c] >= cap:
            raise ValueError(
                f"detection_map: class {c} exceeded the state capacity "
                f"{cap}; raise the TruePos/FalsePos state size")
        buf[c, cnt[c], 0] = score
        buf[c, cnt[c], 1] = flag
        cnt[c] += 1

    wide = gt.shape[-1] >= 6  # rows carry a difficult flag at col 1
    for b in range(det.shape[0]):
        g = gt[b, : int(gt_lens[b])]
        d = det[b, : int(det_lens[b])]
        g_lab = g[:, 0].astype(np.int64)
        g_diff = g[:, 1].astype(bool) if wide else \
            np.zeros(len(g), bool)
        g_box = g[:, 2:6] if wide else g[:, 1:5]
        for c in range(C):
            sel = (g_lab == c) & (evaluate_difficult | ~g_diff)
            pos_count[c] += int(sel.sum())
        matched = np.zeros(len(g), bool)
        order = np.argsort(-d[:, 1], kind="stable")
        for i in order:
            c = int(d[i, 0])
            if c < 0 or c >= C:
                continue
            score = float(d[i, 1])
            box = d[i, 2:6]
            best, best_j = 0.0, -1
            for j in range(len(g)):
                if g_lab[j] != c:
                    continue
                ov = iou(box, g_box[j])
                if ov > best:
                    best, best_j = ov, j
            # strict >, matching detection_map_op.h:401
            if best > overlap_threshold and best_j >= 0:
                if not evaluate_difficult and g_diff[best_j]:
                    continue  # difficult gt: ignore the detection
                if not matched[best_j]:
                    matched[best_j] = True
                    push(true_pos, tp_count, c, score, 1.0)
                else:
                    push(false_pos, fp_count, c, score, 1.0)
            else:
                push(false_pos, fp_count, c, score, 1.0)

    # AP over the ACCUMULATED state
    aps = []
    for c in range(C):
        npos = int(pos_count[c])
        if npos == 0:
            continue
        tps = true_pos[c, : tp_count[c]]
        fps = false_pos[c, : fp_count[c]]
        ent = np.concatenate(
            [np.stack([tps[:, 0], np.ones(len(tps))], 1),
             np.stack([fps[:, 0], np.zeros(len(fps))], 1)])
        if len(ent) == 0:
            aps.append(0.0)
            continue
        ent = ent[np.argsort(-ent[:, 0], kind="stable")]
        tp_cum = np.cumsum(ent[:, 1])
        fp_cum = np.cumsum(1 - ent[:, 1])
        rec = tp_cum / npos
        prec = tp_cum / np.maximum(tp_cum + fp_cum, 1e-12)
        aps.append(_voc_ap(rec, prec, ap_type))
    m_ap = float(np.mean(aps)) if aps else 0.0
    return (pos_count, true_pos, tp_count, false_pos, fp_count,
            np.asarray([m_ap], np.float32))
