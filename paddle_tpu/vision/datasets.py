"""Vision datasets (reference `python/paddle/vision/datasets/`).

Zero-egress environment: downloads are unavailable, so each dataset reads
local files if present (same formats as the reference loaders) and otherwise
raises; `FakeData` provides deterministic synthetic data for tests/benches.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ..io import Dataset


class FakeData(Dataset):
    """Deterministic synthetic image classification data."""

    def __init__(self, num_samples=1000, image_shape=(3, 224, 224),
                 num_classes=1000, transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + idx)
        img = rng.rand(*self.image_shape).astype(np.float32)
        label = np.asarray(rng.randint(0, self.num_classes), dtype=np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return self.num_samples


class MNIST(Dataset):
    """reference `vision/datasets/mnist.py` — same IDX file format."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        self.transform = transform
        if image_path is None or not os.path.exists(image_path):
            raise FileNotFoundError(
                "MNIST files not available (no network); pass image_path/"
                "label_path to local IDX files, or use datasets.FakeData"
            )
        with gzip.open(image_path, "rb") if image_path.endswith(".gz") else open(image_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            self.images = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows, cols)
        with gzip.open(label_path, "rb") if label_path.endswith(".gz") else open(label_path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            self.labels = np.frombuffer(f.read(), dtype=np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None] / 255.0
        label = np.asarray(self.labels[idx], dtype=np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.images)


FashionMNIST = MNIST


class Cifar10(Dataset):
    """reference `vision/datasets/cifar.py` — same pickle batch format."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        self.transform = transform
        if data_file is None or not os.path.exists(data_file):
            raise FileNotFoundError(
                "CIFAR archive not available (no network); pass data_file, "
                "or use datasets.FakeData"
            )
        images, labels = [], []
        with tarfile.open(data_file, "r:gz") as tf:
            names = [m for m in tf.getmembers()
                     if ("data_batch" in m.name if mode == "train" else "test_batch" in m.name)]
            for m in sorted(names, key=lambda x: x.name):
                d = pickle.load(tf.extractfile(m), encoding="bytes")
                images.append(d[b"data"])
                labels += list(d[b"labels"])
        self.images = np.concatenate(images).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, dtype=np.int64)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    pass


class DatasetFolder(Dataset):
    """reference `vision/datasets/folder.py`."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        extensions = extensions or (".png", ".jpg", ".jpeg", ".bmp", ".npy")
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fn in sorted(os.listdir(cdir)):
                if fn.lower().endswith(extensions):
                    self.samples.append((os.path.join(cdir, fn),
                                         self.class_to_idx[c]))
        self.loader = loader or self._default_loader

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        try:
            from PIL import Image

            return np.asarray(Image.open(path).convert("RGB"))
        except ImportError:
            raise RuntimeError("PIL unavailable; use .npy images")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(target, dtype=np.int64)

    def __len__(self):
        return len(self.samples)


ImageFolder = DatasetFolder


class Flowers(Dataset):
    """Flowers-102 classification dataset (reference
    `python/paddle/vision/datasets/flowers.py` — downloads 102flowers.tgz +
    labels .mat).  Zero-egress build: `data_file=` loads a pre-downloaded
    archive directory of .npy images, else a deterministic synthetic corpus
    with the reference's (image, label) schema."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, num_samples=128,
                 image_size=(3, 32, 32)):
        from ..utils import stable_rng

        if data_file is not None or label_file is not None or \
                setid_file is not None:
            raise NotImplementedError(
                "Flowers: archive loading is not implemented in the "
                "zero-egress build; omit data/label/setid files for "
                "synthetic data")
        self.transform = transform
        r = stable_rng("flowers", mode)
        self.images = r.rand(num_samples, *image_size).astype(np.float32)
        self.labels = r.randint(0, 102, (num_samples,)).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class VOC2012(Dataset):
    """VOC2012 segmentation dataset (reference
    `python/paddle/vision/datasets/voc2012.py`): (image, label-mask) pairs.
    Synthetic fallback preserves the schema (HxW class-index mask)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 num_samples=32, image_size=(3, 32, 32), num_classes=21):
        from ..utils import stable_rng

        if data_file is not None:
            raise NotImplementedError(
                "VOC2012: archive loading is not implemented in the "
                "zero-egress build; omit data_file for synthetic data")
        self.transform = transform
        r = stable_rng("voc2012", mode)
        self.images = r.rand(num_samples, *image_size).astype(np.float32)
        self.masks = r.randint(0, num_classes,
                               (num_samples,) + image_size[1:]).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.masks[idx]

    def __len__(self):
        return len(self.images)
