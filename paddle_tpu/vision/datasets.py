"""Vision datasets (reference `python/paddle/vision/datasets/`).

Zero-egress environment: downloads are unavailable, so each dataset reads
local files if present (same formats as the reference loaders) and otherwise
raises; `FakeData` provides deterministic synthetic data for tests/benches.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ..io import Dataset


class FakeData(Dataset):
    """Deterministic synthetic image classification data."""

    def __init__(self, num_samples=1000, image_shape=(3, 224, 224),
                 num_classes=1000, transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + idx)
        img = rng.rand(*self.image_shape).astype(np.float32)
        label = np.asarray(rng.randint(0, self.num_classes), dtype=np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return self.num_samples


class MNIST(Dataset):
    """reference `vision/datasets/mnist.py` — same IDX file format."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        self.transform = transform
        if image_path is None or not os.path.exists(image_path):
            raise FileNotFoundError(
                "MNIST files not available (no network); pass image_path/"
                "label_path to local IDX files, or use datasets.FakeData"
            )
        with gzip.open(image_path, "rb") if image_path.endswith(".gz") else open(image_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            self.images = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows, cols)
        with gzip.open(label_path, "rb") if label_path.endswith(".gz") else open(label_path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            self.labels = np.frombuffer(f.read(), dtype=np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None] / 255.0
        label = np.asarray(self.labels[idx], dtype=np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.images)


FashionMNIST = MNIST


class Cifar10(Dataset):
    """reference `vision/datasets/cifar.py` — same pickle batch format."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        self.transform = transform
        if data_file is None or not os.path.exists(data_file):
            raise FileNotFoundError(
                "CIFAR archive not available (no network); pass data_file, "
                "or use datasets.FakeData"
            )
        images, labels = [], []
        with tarfile.open(data_file, "r:gz") as tf:
            names = [m for m in tf.getmembers()
                     if ("data_batch" in m.name if mode == "train" else "test_batch" in m.name)]
            for m in sorted(names, key=lambda x: x.name):
                d = pickle.load(tf.extractfile(m), encoding="bytes")
                images.append(d[b"data"])
                labels += list(d[b"labels"])
        self.images = np.concatenate(images).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, dtype=np.int64)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    pass


class DatasetFolder(Dataset):
    """reference `vision/datasets/folder.py`."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        extensions = extensions or (".png", ".jpg", ".jpeg", ".bmp", ".npy")
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fn in sorted(os.listdir(cdir)):
                if fn.lower().endswith(extensions):
                    self.samples.append((os.path.join(cdir, fn),
                                         self.class_to_idx[c]))
        self.loader = loader or self._default_loader

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        try:
            from PIL import Image

            return np.asarray(Image.open(path).convert("RGB"))
        except ImportError:
            raise RuntimeError("PIL unavailable; use .npy images")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(target, dtype=np.int64)

    def __len__(self):
        return len(self.samples)


ImageFolder = DatasetFolder


class Flowers(Dataset):
    """Flowers-102 classification dataset (reference
    `python/paddle/vision/datasets/flowers.py` — downloads 102flowers.tgz +
    labels .mat).  Zero-egress build: `data_file=` loads a pre-downloaded
    archive directory of .npy images, else a deterministic synthetic corpus
    with the reference's (image, label) schema."""

    # the reference trains on the LARGE split (tstid) and tests on trnid
    # (`vision/datasets/flowers.py` MODE_FLAG_MAP)
    MODE_FLAG_MAP = {"train": "tstid", "test": "trnid", "valid": "valid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, num_samples=128,
                 image_size=(3, 32, 32)):
        from ..utils import stable_rng

        self.transform = transform
        self._archive = None
        if data_file is not None:
            if label_file is None or setid_file is None:
                raise ValueError(
                    "Flowers: data_file requires label_file "
                    "(imagelabels.mat) and setid_file (setid.mat) too")
            import scipy.io as scio

            self._archive = _TarImages(data_file)
            self.labels = scio.loadmat(label_file)["labels"][0]
            self.indexes = scio.loadmat(setid_file)[
                self.MODE_FLAG_MAP[mode.lower()]][0]
            return
        if label_file is not None or setid_file is not None:
            raise ValueError(
                "Flowers: label_file/setid_file given without data_file")
        r = stable_rng("flowers", mode)
        self.images = r.rand(num_samples, *image_size).astype(np.float32)
        # same schema as the archive path: 1-based labels, shape (1,)
        # (the reference's imagelabels.mat is 1-based)
        self.labels = r.randint(1, 103, (num_samples,)).astype(np.int64)

    def __getitem__(self, idx):
        if self._archive is not None:
            index = int(self.indexes[idx])
            img = self._archive.read_image("jpg/image_%05d.jpg" % index)
            label = np.array([self.labels[index - 1]]).astype(np.int64)
            if self.transform is not None:
                img = self.transform(img)
            return img, label
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array([self.labels[idx]]).astype(np.int64)

    def __len__(self):
        if self._archive is not None:
            return len(self.indexes)
        return len(self.images)


class _TarImages:
    """Member-indexed tar archive with PIL image decode (reference
    pattern: `vision/datasets/voc2012.py:120` name2mem + extractfile).
    The handle is opened lazily and dropped on pickling so archive-backed
    datasets survive the spawn-based multiprocess DataLoader (each worker
    re-opens its own handle)."""

    def __init__(self, path):
        self.path = path
        self._tar = None
        self._name2mem = None
        self._members()  # validate the archive eagerly

    def _members(self):
        import tarfile

        if self._tar is None:
            self._tar = tarfile.open(self.path)
            self._name2mem = {m.name: m for m in self._tar.getmembers()}
        return self._tar, self._name2mem

    def __getstate__(self):
        return {"path": self.path}

    def __setstate__(self, state):
        self.path = state["path"]
        self._tar = None
        self._name2mem = None

    def close(self):
        if self._tar is not None:
            self._tar.close()
            self._tar = None
            self._name2mem = None

    def read_text_lines(self, name):
        tar, n2m = self._members()
        data = tar.extractfile(n2m[name]).read()
        return [ln.strip() for ln in data.decode("utf-8").splitlines()
                if ln.strip()]

    def read_image(self, name):
        import io as _io

        from PIL import Image

        tar, n2m = self._members()
        raw = tar.extractfile(n2m[name]).read()
        return np.array(Image.open(_io.BytesIO(raw)))


class VOC2012(Dataset):
    """VOC2012 segmentation dataset (reference
    `python/paddle/vision/datasets/voc2012.py`): (image, label-mask) pairs.
    Synthetic fallback preserves the schema (HxW class-index mask)."""

    SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
    DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
    LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"
    MODE_FLAG_MAP = {"train": "train", "test": "train", "valid": "val"}

    def __init__(self, data_file=None, mode="train", transform=None,
                 num_samples=32, image_size=(3, 32, 32), num_classes=21):
        from ..utils import stable_rng

        self.transform = transform
        self._archive = None
        if data_file is not None:
            self._archive = _TarImages(data_file)
            flag = self.MODE_FLAG_MAP[mode.lower()]
            names = self._archive.read_text_lines(self.SET_FILE.format(flag))
            self.data = [self.DATA_FILE.format(n) for n in names]
            self.labels = [self.LABEL_FILE.format(n) for n in names]
            return
        r = stable_rng("voc2012", mode)
        self.images = r.rand(num_samples, *image_size).astype(np.float32)
        self.masks = r.randint(0, num_classes,
                               (num_samples,) + image_size[1:]).astype(np.int64)

    def __getitem__(self, idx):
        if self._archive is not None:
            img = self._archive.read_image(self.data[idx])
            mask = self._archive.read_image(self.labels[idx]).astype(np.int64)
            if self.transform is not None:
                img = self.transform(img)
            return img, mask
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.masks[idx]

    def __len__(self):
        if self._archive is not None:
            return len(self.data)
        return len(self.images)
