"""Vision / detection operators.

TPU-native re-design of the reference detection op family
(`paddle/fluid/operators/detection/`: `yolo_box_op.*`, `multiclass_nms_op.cc`,
`roi_align_op.*`, `prior_box_op.*`, `box_coder_op.*`, `iou_similarity_op.*`,
`box_clip_op.*`; Python API `python/paddle/vision/ops.py` and
`fluid/layers/detection.py`).

Design notes (XLA-first):
- All outputs are **static-shape**: NMS-style ops return fixed-size padded
  results plus a valid count instead of the reference's variable-length
  LoDTensor outputs (dynamic shapes don't compile on TPU).
- NMS uses the sort + O(n^2) suppression-mask formulation (one fori_loop,
  no data-dependent shapes) instead of the reference's CPU greedy loop.
- roi_align vectorizes the bilinear sampling grid with vmap/gather so the
  whole op is a couple of gathers + reductions (MXU/VPU friendly).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import dispatch
from ..core.tensor import Tensor, unwrap

__all__ = [
    "iou_similarity", "box_clip", "box_coder", "prior_box", "yolo_box",
    "roi_align", "roi_pool", "nms", "multiclass_nms", "matrix_nms",
    "deform_conv2d", "correlation", "bilateral_slice",
]


def __getattr__(name):
    # detection long-tail ops live in vision.detection but are reachable
    # through vision.ops for reference API parity (fluid/layers/detection)
    from . import detection as _det

    if name in _det.__all__:
        return getattr(_det, name)
    raise AttributeError(name)


# ---------------------------------------------------------------------------
# IoU / box utilities
# ---------------------------------------------------------------------------
def _pairwise_iou(a, b, box_normalized=True):
    """a: [N,4], b: [M,4] in xyxy -> [N,M] IoU."""
    off = 0.0 if box_normalized else 1.0
    ax1, ay1, ax2, ay2 = a[:, 0], a[:, 1], a[:, 2], a[:, 3]
    bx1, by1, bx2, by2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    area_a = (ax2 - ax1 + off) * (ay2 - ay1 + off)
    area_b = (bx2 - bx1 + off) * (by2 - by1 + off)
    ix1 = jnp.maximum(ax1[:, None], bx1[None, :])
    iy1 = jnp.maximum(ay1[:, None], by1[None, :])
    ix2 = jnp.minimum(ax2[:, None], bx2[None, :])
    iy2 = jnp.minimum(ay2[:, None], by2[None, :])
    iw = jnp.clip(ix2 - ix1 + off, 0)
    ih = jnp.clip(iy2 - iy1 + off, 0)
    inter = iw * ih
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def iou_similarity(x, y, box_normalized=True, name=None):
    """[N,4] x [M,4] -> [N,M] IoU matrix.
    Reference: `operators/detection/iou_similarity_op.{h,cc}`."""
    return dispatch(functools.partial(_pairwise_iou,
                                      box_normalized=box_normalized), x, y)


def box_clip(input, im_info, name=None):
    """Clip boxes to image boundaries.
    Reference: `operators/detection/box_clip_op.{h,cc}` (im_info = [H, W,
    scale]; boxes clipped to [0, dim/scale - 1])."""
    def f(boxes, info):
        h, w, scale = info[0], info[1], info[2]
        hmax = h / scale - 1.0
        wmax = w / scale - 1.0
        x1 = jnp.clip(boxes[..., 0], 0, wmax)
        y1 = jnp.clip(boxes[..., 1], 0, hmax)
        x2 = jnp.clip(boxes[..., 2], 0, wmax)
        y2 = jnp.clip(boxes[..., 3], 0, hmax)
        return jnp.stack([x1, y1, x2, y2], axis=-1)

    return dispatch(f, input, im_info)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              name=None):
    """Encode/decode boxes against priors.
    Reference: `operators/detection/box_coder_op.{h,cc,cu}`."""
    norm = box_normalized

    def f(prior, target, *var_args):
        var = var_args[0] if var_args else None
        off = 0.0 if norm else 1.0
        pw = prior[:, 2] - prior[:, 0] + off
        ph = prior[:, 3] - prior[:, 1] + off
        pcx = prior[:, 0] + pw * 0.5
        pcy = prior[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            # target: [M,4]; output [M, N, 4] for N priors
            tw = target[:, 2] - target[:, 0] + off
            th = target[:, 3] - target[:, 1] + off
            tcx = target[:, 0] + tw * 0.5
            tcy = target[:, 1] + th * 0.5
            ox = (tcx[:, None] - pcx[None, :]) / pw[None, :]
            oy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
            ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
            oh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
            out = jnp.stack([ox, oy, ow, oh], axis=-1)
            if var is not None:
                out = out / var.reshape((1, -1, 4)) if var.ndim == 2 else out / var.reshape((1, 1, 4))
            return out
        # decode_center_size: target [N, M, 4] deltas against priors
        if axis == 0:
            pw_, ph_, pcx_, pcy_ = (v[None, :, None] for v in (pw, ph, pcx, pcy))
        else:
            pw_, ph_, pcx_, pcy_ = (v[:, None, None] for v in (pw, ph, pcx, pcy))
        t = target
        if var is not None:
            v4 = var.reshape((1, -1, 4)) if var.ndim == 2 else var.reshape((1, 1, 4))
            if axis == 1 and var.ndim == 2:
                v4 = var.reshape((-1, 1, 4))
            t = t * v4
        cx = t[..., 0:1] * pw_ + pcx_
        cy = t[..., 1:2] * ph_ + pcy_
        w = jnp.exp(t[..., 2:3]) * pw_
        h = jnp.exp(t[..., 3:4]) * ph_
        return jnp.concatenate([cx - w * 0.5, cy - h * 0.5,
                                cx + w * 0.5 - off, cy + h * 0.5 - off],
                               axis=-1)

    if prior_box_var is None:
        return dispatch(f, prior_box, target_box)
    if isinstance(prior_box_var, (list, tuple)):
        prior_box_var = Tensor(jnp.asarray(prior_box_var, jnp.float32))
    return dispatch(f, prior_box, target_box, prior_box_var)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior (anchor) box generation.
    Reference: `operators/detection/prior_box_op.{h,cc,cu}`.  Anchor layout
    is computed host-side with numpy (shapes are static attributes); only
    the (constant) result lives on device — there is no per-step compute.
    Returns (boxes [H,W,P,4], variances [H,W,P,4])."""
    in_h, in_w = int(unwrap(input).shape[2]), int(unwrap(input).shape[3])
    img_h, img_w = int(unwrap(image).shape[2]), int(unwrap(image).shape[3])
    step_w = steps[0] or img_w / in_w
    step_h = steps[1] or img_h / in_h

    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))

    widths, heights = [], []
    maxs = list(max_sizes) if max_sizes else [None] * len(min_sizes)
    for ms, mx in zip(min_sizes, maxs):
        if min_max_aspect_ratios_order:
            widths.append(ms); heights.append(ms)
            if mx is not None:
                widths.append(np.sqrt(ms * mx)); heights.append(np.sqrt(ms * mx))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                widths.append(ms * np.sqrt(ar)); heights.append(ms / np.sqrt(ar))
        else:
            for ar in ars:
                widths.append(ms * np.sqrt(ar)); heights.append(ms / np.sqrt(ar))
            if mx is not None:
                widths.append(np.sqrt(ms * mx)); heights.append(np.sqrt(ms * mx))

    num_priors = len(widths)
    cx = (np.arange(in_w) + offset) * step_w
    cy = (np.arange(in_h) + offset) * step_h
    cxg, cyg = np.meshgrid(cx, cy)  # [H, W]
    w = np.asarray(widths, np.float32) * 0.5
    h = np.asarray(heights, np.float32) * 0.5
    boxes = np.stack([
        (cxg[..., None] - w) / img_w,
        (cyg[..., None] - h) / img_h,
        (cxg[..., None] + w) / img_w,
        (cyg[..., None] + h) / img_h,
    ], axis=-1).astype(np.float32)  # [H, W, P, 4]
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    vars_ = np.broadcast_to(np.asarray(variance, np.float32),
                            (in_h, in_w, num_priors, 4)).copy()
    return Tensor(jnp.asarray(boxes)), Tensor(jnp.asarray(vars_))


# ---------------------------------------------------------------------------
# YOLO
# ---------------------------------------------------------------------------
def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, scale_x_y=1.0, name=None):
    """Decode YOLOv3 head output into boxes + scores.
    Reference: `operators/detection/yolo_box_op.{h,cc,cu}`.
    x: [N, A*(5+C), H, W]; img_size: [N, 2] (h, w) int.
    Returns boxes [N, H*W*A, 4] (xyxy in image scale) and
    scores [N, H*W*A, C]; boxes with conf < conf_thresh are zeroed."""
    na = len(anchors) // 2
    anchors_a = jnp.asarray(anchors, jnp.float32).reshape(na, 2)

    def f(xv, imgs):
        n, _, h, w = xv.shape
        xv = xv.reshape(n, na, 5 + class_num, h, w)
        grid_x = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
        grid_y = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
        bias = 0.5 * (scale_x_y - 1.0)
        cx = (jax.nn.sigmoid(xv[:, :, 0]) * scale_x_y - bias + grid_x) / w
        cy = (jax.nn.sigmoid(xv[:, :, 1]) * scale_x_y - bias + grid_y) / h
        input_w = downsample_ratio * w
        input_h = downsample_ratio * h
        bw = jnp.exp(xv[:, :, 2]) * anchors_a[None, :, 0, None, None] / input_w
        bh = jnp.exp(xv[:, :, 3]) * anchors_a[None, :, 1, None, None] / input_h
        conf = jax.nn.sigmoid(xv[:, :, 4])
        probs = jax.nn.sigmoid(xv[:, :, 5:])  # [n, a, C, h, w]
        score = conf[:, :, None] * probs
        keep = (conf >= conf_thresh).astype(xv.dtype)

        imgh = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        imgw = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (cx - bw * 0.5) * imgw
        y1 = (cy - bh * 0.5) * imgh
        x2 = (cx + bw * 0.5) * imgw
        y2 = (cy + bh * 0.5) * imgh
        if clip_bbox:
            x1 = jnp.clip(x1, 0.0, imgw - 1.0)
            y1 = jnp.clip(y1, 0.0, imgh - 1.0)
            x2 = jnp.clip(x2, 0.0, imgw - 1.0)
            y2 = jnp.clip(y2, 0.0, imgh - 1.0)
        boxes = jnp.stack([x1, y1, x2, y2], axis=2) * keep[:, :, None]
        scores = score * keep[:, :, None]
        # [n, a, 4|C, h, w] -> [n, h*w*a, 4|C] (reference order: h, w, a
        # fastest-varying a? yolo_box_op iterates (a, h, w) row-major)
        boxes = boxes.transpose(0, 1, 3, 4, 2).reshape(n, na * h * w, 4)
        scores = scores.transpose(0, 1, 3, 4, 2).reshape(n, na * h * w,
                                                         class_num)
        return boxes, scores

    return dispatch(f, x, img_size, nondiff=(1,))


# ---------------------------------------------------------------------------
# ROI pooling
# ---------------------------------------------------------------------------
def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """ROIAlign (bilinear-sampled average pooling over regions).
    Reference: `operators/roi_align_op.{h,cc,cu}`; Python
    `python/paddle/vision/ops.py` (later line) / `fluid/layers/detection.py`.
    x: [N, C, H, W]; boxes: [R, 4] xyxy in input-image coords;
    boxes_num: [N] int — number of rois per batch image (prefix-partitioned,
    the LoD replacement).  Returns [R, C, out_h, out_w]."""
    if isinstance(output_size, int):
        out_h = out_w = output_size
    else:
        out_h, out_w = output_size
    ratio = sampling_ratio if sampling_ratio > 0 else 2

    def f(xv, rois, rois_num):
        n, c, h, w = xv.shape
        # batch index of each roi from boxes_num prefix sums
        ends = jnp.cumsum(rois_num)
        roi_batch = jnp.sum(
            (jnp.arange(rois.shape[0])[:, None] >= ends[None, :]).astype(
                jnp.int32), axis=1)  # [R]
        offset = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - offset
        y1 = rois[:, 1] * spatial_scale - offset
        x2 = rois[:, 2] * spatial_scale - offset
        y2 = rois[:, 3] * spatial_scale - offset
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bin_w = rw / out_w
        bin_h = rh / out_h

        # sampling grid: for each roi, out_h*ratio x out_w*ratio points
        gy = (jnp.arange(out_h * ratio) + 0.5) / ratio  # in bin units
        gx = (jnp.arange(out_w * ratio) + 0.5) / ratio
        sy = y1[:, None] + bin_h[:, None] * gy[None, :]  # [R, out_h*ratio]
        sx = x1[:, None] + bin_w[:, None] * gx[None, :]  # [R, out_w*ratio]

        def bilinear(img, ys, xs):
            # img: [C, H, W]; ys: [Sy], xs: [Sx] -> [C, Sy, Sx]
            y0 = jnp.clip(jnp.floor(ys), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xs), 0, w - 1)
            y1i = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
            x1i = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
            y0i = y0.astype(jnp.int32)
            x0i = x0.astype(jnp.int32)
            ly = jnp.clip(ys, 0, h - 1) - y0
            lx = jnp.clip(xs, 0, w - 1) - x0
            v00 = img[:, y0i][:, :, x0i]
            v01 = img[:, y0i][:, :, x1i]
            v10 = img[:, y1i][:, :, x0i]
            v11 = img[:, y1i][:, :, x1i]
            wy = ly[None, :, None]
            wx = lx[None, None, :]
            val = (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                   v10 * wy * (1 - wx) + v11 * wy * wx)
            # zero out samples outside the feature map (reference clamps
            # but marks empty only when roi is degenerate; we follow clamp)
            return val

        def per_roi(b, ys, xs):
            img = xv[b]  # [C, H, W]
            samples = bilinear(img, ys, xs)  # [C, out_h*r, out_w*r]
            samples = samples.reshape(c, out_h, ratio, out_w, ratio)
            return samples.mean(axis=(2, 4))

        return jax.vmap(per_roi)(roi_batch, sy, sx)

    return dispatch(f, x, boxes, boxes_num, nondiff=(2,))


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Max-pool ROI pooling, exact reference semantics
    (`operators/roi_pool_op.h`): integer bin ranges
    ``hstart = floor(ph*bin_h)+y1 .. hend = ceil((ph+1)*bin_h)+y1`` and a
    true max over every pixel in the bin (empty bins output 0).  Realized
    as static-shape row/col masks + masked max so XLA sees fixed shapes —
    no sampling approximation."""
    if isinstance(output_size, int):
        out_h = out_w = output_size
    else:
        out_h, out_w = output_size

    def f(xv, rois, rois_num):
        n, c, h, w = xv.shape
        ends = jnp.cumsum(rois_num)
        roi_batch = jnp.sum(
            (jnp.arange(rois.shape[0])[:, None] >= ends[None, :]).astype(
                jnp.int32), axis=1)

        def cround(v):
            # C round(): half away from zero (jnp.round is half-to-even,
            # which shifts ROI edges at .5 boundaries, e.g. scale 1/16)
            return jnp.sign(v) * jnp.floor(jnp.abs(v) + 0.5)

        x1 = cround(rois[:, 0] * spatial_scale)
        y1 = cround(rois[:, 1] * spatial_scale)
        x2 = cround(rois[:, 2] * spatial_scale)
        y2 = cround(rois[:, 3] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        bin_h = rh / out_h  # [R]
        bin_w = rw / out_w
        ph = jnp.arange(out_h, dtype=jnp.float32)
        pw = jnp.arange(out_w, dtype=jnp.float32)
        # integer bin edges, clipped into the feature map ([R, out])
        hs = jnp.clip(jnp.floor(ph[None, :] * bin_h[:, None]) + y1[:, None],
                      0, h)
        he = jnp.clip(jnp.ceil((ph[None, :] + 1) * bin_h[:, None])
                      + y1[:, None], 0, h)
        ws = jnp.clip(jnp.floor(pw[None, :] * bin_w[:, None]) + x1[:, None],
                      0, w)
        we = jnp.clip(jnp.ceil((pw[None, :] + 1) * bin_w[:, None])
                      + x1[:, None], 0, w)
        hidx = jnp.arange(h, dtype=jnp.float32)
        widx = jnp.arange(w, dtype=jnp.float32)
        # [R, out_h, H] / [R, out_w, W] membership masks
        rowmask = (hidx[None, None, :] >= hs[:, :, None]) & \
                  (hidx[None, None, :] < he[:, :, None])
        colmask = (widx[None, None, :] >= ws[:, :, None]) & \
                  (widx[None, None, :] < we[:, :, None])
        neg = jnp.asarray(-jnp.inf, xv.dtype)

        def per_roi(b, rm, cm):
            img = xv[b]  # [C, H, W]
            # max over rows in each bin-row: [C, out_h, W]
            rowmax = jnp.where(rm[None, :, :, None], img[:, None, :, :],
                               neg).max(axis=2)
            # then max over cols in each bin-col: [C, out_h, out_w]
            full = jnp.where(cm[None, None, :, :], rowmax[:, :, None, :],
                             neg).max(axis=3)
            return jnp.where(jnp.isfinite(full), full, 0.0).astype(xv.dtype)

        return jax.vmap(per_roi)(roi_batch, rowmask, colmask)

    return dispatch(f, x, boxes, boxes_num, nondiff=(2,))


# ---------------------------------------------------------------------------
# NMS family
# ---------------------------------------------------------------------------
def _nms_keep_mask(boxes, scores, iou_threshold, box_normalized=True,
                   eta=1.0):
    """Sorted greedy NMS as a fori_loop over a suppression mask.
    boxes: [N,4], scores: [N] -> keep mask [N] (bool, in original order).
    eta < 1 enables the reference's adaptive threshold: after each kept
    box, while thresh > 0.5 it decays by eta (multiclass_nms_op.cc)."""
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    b = boxes[order]
    iou = _pairwise_iou(b, b, box_normalized)

    def body(i, carry):
        keep, thresh = carry
        # candidate i survives iff no earlier kept box overlaps it
        sup = jnp.any(jnp.where(jnp.arange(n) < i,
                                (iou[i] > thresh) & keep, False))
        keep = keep.at[i].set(~sup)
        if eta < 1.0:
            thresh = jnp.where(~sup & (thresh > 0.5), thresh * eta, thresh)
        return keep, thresh

    init = jnp.zeros((n,), jnp.bool_)
    if n:
        init = init.at[0].set(True)
    keep_sorted, _ = jax.lax.fori_loop(
        1, n, body, (init, jnp.asarray(iou_threshold, jnp.float32)))
    keep = jnp.zeros((n,), jnp.bool_).at[order].set(keep_sorted)
    return keep


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Greedy NMS; returns indices of kept boxes sorted by score (padded
    behavior: eager-only — returns a dynamic-length index tensor like the
    reference CPU op; use `multiclass_nms` inside compiled graphs).
    Reference: `operators/detection/nms_op`(v2.1-era python in
    fluid/layers/detection.py)."""
    b = unwrap(boxes)
    s = unwrap(scores) if scores is not None else jnp.ones((b.shape[0],))
    if category_idxs is not None:
        # category-aware: offset boxes per category so cross-class pairs
        # never overlap (standard batched-NMS trick)
        c = unwrap(category_idxs).astype(b.dtype)
        off = (c * (b.max() + 1.0))[:, None]
        keep = _nms_keep_mask(b + off, s, iou_threshold)
    else:
        keep = _nms_keep_mask(b, s, iou_threshold)
    idx = jnp.nonzero(keep)[0]
    idx = idx[jnp.argsort(-s[idx])]
    if top_k is not None:
        idx = idx[:top_k]
    return Tensor(idx)


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=-1, return_index=False, rois_num=None,
                   name=None):
    """Static-shape multiclass NMS.
    Reference: `operators/detection/multiclass_nms_op.cc` (CPU, dynamic
    LoD output).  TPU-native: fixed [N, keep_top_k, 6] output
    (label, score, x1, y1, x2, y2), invalid slots filled with -1, plus a
    [N] valid-count tensor (replaces the LoD offsets).
    bboxes: [N, M, 4]; scores: [N, C, M].  `rois_num` (the reference's
    LoD-batched roi input) is not supported — pass per-image dense boxes."""
    if rois_num is not None:
        raise NotImplementedError(
            "multiclass_nms(rois_num=...) LoD-batched input is not "
            "supported; pass dense [N, M, 4] boxes")

    def f(bb, sc):
        n, m, _ = bb.shape
        c = sc.shape[1]

        def per_image(boxes_i, scores_i):
            # per class: mask by score_threshold, NMS, then global top-k
            def per_class(cls_scores):
                valid = cls_scores > score_threshold
                s = jnp.where(valid, cls_scores, -jnp.inf)
                if nms_top_k > 0 and nms_top_k < m:
                    topv, topi = jax.lax.top_k(s, nms_top_k)
                else:
                    topi = jnp.argsort(-s)
                    topv = s[topi]
                b = boxes_i[topi]
                keep = _nms_keep_mask(b, topv, nms_threshold, normalized,
                                      eta=nms_eta)
                keep &= jnp.isfinite(topv)
                return topv, topi, keep

            topv, topi, keep = jax.vmap(per_class)(scores_i)  # [C, K]
            k = topv.shape[1]
            labels = jnp.broadcast_to(jnp.arange(c)[:, None], (c, k))
            if background_label >= 0:
                keep &= labels != background_label
            flat_s = jnp.where(keep, topv, -jnp.inf).reshape(-1)
            flat_l = labels.reshape(-1)
            flat_i = topi.reshape(-1)
            pool = flat_s.shape[0]
            take = min(keep_top_k, pool) if keep_top_k > 0 else pool
            sel_s, sel = jax.lax.top_k(flat_s, take)
            sel_l = flat_l[sel].astype(bb.dtype)
            sel_b = boxes_i[flat_i[sel]]
            valid = jnp.isfinite(sel_s)
            out = jnp.concatenate([
                jnp.where(valid, sel_l, -1.0)[:, None],
                jnp.where(valid, sel_s, -1.0)[:, None],
                jnp.where(valid[:, None], sel_b, -1.0),
            ], axis=1)
            out_idx = flat_i[sel]
            if keep_top_k > 0 and take < keep_top_k:
                # keep the documented fixed [keep_top_k, 6] shape even when
                # the candidate pool (C * nms_top_k) is smaller
                pad = keep_top_k - take
                out = jnp.concatenate(
                    [out, jnp.full((pad, 6), -1.0, out.dtype)], axis=0)
                out_idx = jnp.concatenate(
                    [out_idx, jnp.full((pad,), -1, out_idx.dtype)])
            # counts/indices are integer outputs: keep them off the vjp
            # graph so backward never needs cotangents for them
            return out, jax.lax.stop_gradient(
                valid.sum().astype(jnp.int32)), jax.lax.stop_gradient(out_idx)

        outs, counts, idxs = jax.vmap(per_image)(bb, sc)
        return outs, counts, idxs

    out, counts, index = dispatch(f, bboxes, scores)
    if return_index:
        return out, index, counts
    return out, counts


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2.
    Reference: `operators/deformable_conv_op.*`, `deformable_conv_v1_op.*`;
    Python `python/paddle/vision/ops.py deform_conv2d`.

    TPU-native: instead of the reference's im2col-with-offsets CUDA kernel,
    sampling locations are materialized as one bilinear gather per kernel
    tap and the contraction is a single einsum (MXU) — no scatter, static
    shapes.  x: [N, Cin, H, W]; offset: [N, 2*dg*kh*kw, Ho, Wo];
    mask (v2): [N, dg*kh*kw, Ho, Wo]; weight: [Cout, Cin/groups, kh, kw].
    """
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    d = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)

    def f(xv, off, w, *rest):
        m = rest[0] if mask is not None else None
        b = (rest[-1] if bias is not None else None)
        n, cin, h, wd = xv.shape
        cout, cin_g, kh, kw = w.shape
        ho = (h + 2 * p[0] - d[0] * (kh - 1) - 1) // s[0] + 1
        wo = (wd + 2 * p[1] - d[1] * (kw - 1) - 1) // s[1] + 1
        dg = deformable_groups
        off = off.reshape(n, dg, kh * kw, 2, ho, wo)

        base_y = jnp.arange(ho) * s[0] - p[0]  # [Ho]
        base_x = jnp.arange(wo) * s[1] - p[1]  # [Wo]
        ky = jnp.arange(kh) * d[0]
        kx = jnp.arange(kw) * d[1]
        # sample positions per tap: [kh*kw, Ho, Wo]
        shape = (kh, kw, ho, wo)
        taps_y = jnp.broadcast_to(ky[:, None, None, None] +
                                  base_y[None, None, :, None], shape)
        taps_x = jnp.broadcast_to(kx[None, :, None, None] +
                                  base_x[None, None, None, :], shape)
        taps_y = taps_y.reshape(kh * kw, ho, wo)
        taps_x = taps_x.reshape(kh * kw, ho, wo)

        # offsets are (dy, dx) per deformable group and tap
        sy = taps_y[None, None] + off[:, :, :, 0]  # [N, dg, K, Ho, Wo]
        sx = taps_x[None, None] + off[:, :, :, 1]

        y0 = jnp.floor(sy)
        x0 = jnp.floor(sx)
        ly = sy - y0
        lx = sx - x0

        def gather(img_dg, yi, xi):
            # img_dg: [N, dg, C/dg, H, W]; yi/xi: [N, dg, K, Ho, Wo] float.
            # Zero-padded sampling (reference deformable im2col): a corner
            # OUTSIDE the image contributes 0, not a replicated edge pixel.
            valid = ((yi >= 0) & (yi <= h - 1) &
                     (xi >= 0) & (xi <= wd - 1))
            yic = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
            xic = jnp.clip(xi, 0, wd - 1).astype(jnp.int32)
            flat = img_dg.reshape(n, dg, cin // dg, h * wd)
            idx = (yic * wd + xic).reshape(n, dg, 1, -1)
            idx = jnp.broadcast_to(idx, (n, dg, cin // dg, idx.shape[-1]))
            out = jnp.take_along_axis(flat, idx, axis=-1)
            out = out.reshape(n, dg, cin // dg, kh * kw, ho, wo)
            return out * valid[:, :, None].astype(out.dtype)

        img_dg = xv.reshape(n, dg, cin // dg, h, wd)
        v00 = gather(img_dg, y0, x0)
        v01 = gather(img_dg, y0, x0 + 1)
        v10 = gather(img_dg, y0 + 1, x0)
        v11 = gather(img_dg, y0 + 1, x0 + 1)
        wy = ly[:, :, None]
        wx = lx[:, :, None]
        val = (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
               v10 * wy * (1 - wx) + v11 * wy * wx)
        if m is not None:
            val = val * m.reshape(n, dg, 1, kh * kw, ho, wo)

        cols = val.reshape(n, cin, kh * kw, ho, wo)
        wflat = w.reshape(groups, cout // groups, cin_g, kh * kw)
        cols_g = cols.reshape(n, groups, cin // groups, kh * kw, ho, wo)
        out = jnp.einsum("ngckyx,gock->ngoyx", cols_g, wflat)
        out = out.reshape(n, cout, ho, wo)
        if b is not None:
            out = out + b.reshape(1, -1, 1, 1)
        return out

    args = [x, offset, weight]
    if mask is not None:
        args.append(mask)
    if bias is not None:
        args.append(bias)
    return dispatch(f, *args)


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=-1, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (`operators/detection/matrix_nms_op.cc`, SOLOv2): scores
    decay by the max IoU with any higher-scored same-class box — fully
    parallel (one IoU matrix + reductions), which is exactly the
    suppression formulation that suits the TPU (no sequential greedy loop).
    bboxes: [N, M, 4]; scores: [N, C, M].
    Returns out [N, keep_top_k, 6] (label, score, box), valid counts [N]
    (and indices when return_index)."""
    def f(bb, sc):
        n, m, _ = bb.shape
        c = sc.shape[1]

        def per_image(boxes_i, scores_i):
            def per_class(cls_scores):
                k = min(nms_top_k, m) if nms_top_k > 0 else m
                topv, topi = jax.lax.top_k(
                    jnp.where(cls_scores > score_threshold, cls_scores,
                              -jnp.inf), k)
                b = boxes_i[topi]
                iou = _pairwise_iou(b, b, normalized)
                upper = jnp.triu(jnp.ones((k, k), bool), 1)
                # decay per box j: worst (min) over higher-ranked i of
                # decay(iou_ij) relative to how compressed i already is
                ious = jnp.where(upper, iou, 0.0)
                iou_cmax = ious.max(axis=0)  # [k] max IoU of i with any above
                if use_gaussian:
                    # reference decay_score<T,true>: exp((max^2 - iou^2)*sigma)
                    decay = jnp.exp((jnp.square(iou_cmax)[:, None] -
                                     jnp.square(ious)) * gaussian_sigma)
                else:
                    denom = jnp.maximum(1.0 - iou_cmax[:, None], 1e-10)
                    decay = (1.0 - ious) / denom
                decay = jnp.where(upper, decay, 1.0).min(axis=0)  # [k]
                newv = jnp.where(jnp.isfinite(topv), topv * decay, -jnp.inf)
                return newv, topi

            vals, idxs = jax.vmap(per_class)(scores_i)  # [C, K]
            k = vals.shape[1]
            labels = jnp.broadcast_to(jnp.arange(c)[:, None], (c, k))
            if background_label >= 0:
                vals = jnp.where(labels == background_label, -jnp.inf, vals)
            flat_v = jnp.where(vals > post_threshold, vals,
                               -jnp.inf).reshape(-1)
            flat_l = labels.reshape(-1)
            flat_i = idxs.reshape(-1)
            take = min(keep_top_k, flat_v.shape[0]) if keep_top_k > 0 \
                else flat_v.shape[0]
            sel_v, sel = jax.lax.top_k(flat_v, take)
            valid = jnp.isfinite(sel_v)
            sel_b = boxes_i[flat_i[sel]]
            out = jnp.concatenate([
                jnp.where(valid, flat_l[sel].astype(bb.dtype), -1.0)[:, None],
                jnp.where(valid, sel_v, -1.0)[:, None],
                jnp.where(valid[:, None], sel_b, -1.0),
            ], axis=1)
            if keep_top_k > 0 and take < keep_top_k:
                pad = keep_top_k - take
                out = jnp.concatenate(
                    [out, jnp.full((pad, 6), -1.0, out.dtype)], axis=0)
            return (out, jax.lax.stop_gradient(
                valid.sum().astype(jnp.int32)),
                jax.lax.stop_gradient(flat_i[sel]))

        return jax.vmap(per_image)(bb, sc)

    out, counts, index = dispatch(f, bboxes, scores)
    if return_index:
        return out, index, counts
    return out, counts


def correlation(x1, x2, pad_size, kernel_size, max_displacement, stride1,
                stride2, corr_type_multiply=1, name=None):
    """FlowNet correlation volume (`operators/correlation_op.cu`): for each
    displacement (ti, tj) within max_displacement (step stride2), the
    channel-and-kernel-window mean of x1 * shift(x2), sampled on a
    stride1 grid offset by max_displacement into the padded frame.
    Output [N, (2*max_displacement//stride2 + 1)^2, OH, OW] with
    OH = ceil((H + 2*pad - 2*(max_displacement + kernel_radius)) / stride1)
    (reference CorrelationOutputSize, border_radius = kernel_radius +
    max_displacement)."""
    K = int(kernel_size)
    krad = (K - 1) // 2
    rad = int(max_displacement) // int(stride2)
    if int(max_displacement) < krad:
        raise ValueError("correlation: max_displacement must be >= "
                         "kernel radius")

    def f(a, b):
        from jax import lax

        n, c, h, w = a.shape
        p = int(pad_size)
        border = int(max_displacement) + krad
        pa = jnp.pad(a, ((0, 0), (0, 0), (p, p), (p, p)))
        pb = jnp.pad(b, ((0, 0), (0, 0), (p, p), (p, p)))
        oh = int(np.ceil((h + 2 * p - 2 * border) / stride1))
        ow = int(np.ceil((w + 2 * p - 2 * border) / stride1))
        nelems = K * K * c
        start = int(max_displacement) - krad
        planes = []
        for tj in range(-rad, rad + 1):
            for ti in range(-rad, rad + 1):
                shifted = jnp.roll(pb, (-tj * stride2, -ti * stride2),
                                   axis=(2, 3))
                prod = (pa * shifted).sum(axis=1)  # [N, PH, PW]
                win = lax.reduce_window(
                    prod, 0.0, lax.add, (1, K, K), (1, 1, 1), "VALID")
                sub = lax.slice(
                    win, (0, start, start),
                    (n, start + (oh - 1) * stride1 + 1,
                     start + (ow - 1) * stride1 + 1),
                    (1, int(stride1), int(stride1)))
                planes.append(sub / nelems)
        return jnp.stack(planes, axis=1)

    return dispatch(f, x1, x2)


def bilateral_slice(x, grid, guide, has_offset=False, name=None):
    """HDRNet bilateral-grid slice-and-apply
    (`operators/bilateral_slice_op.cu`): per output pixel, trilinearly
    sample per-channel affine coefficients from the bilateral grid at
    (x/w*gw, y/h*gh, guide*gd) with tent weights, then apply them to the
    input channels (+1 offset row when has_offset).
    x [N, Ci, H, W]; grid [N, Cg, gd, gh, gw] with
    Cg = (Ci (+1 if has_offset)) * Co; guide [N, H, W].
    Returns [N, Co, H, W]."""

    def f(xv, gv, guide_v):
        n, ci, h, w = xv.shape
        _, cg, gd, gh, gw = gv.shape
        stride = ci + (1 if has_offset else 0)
        if cg % stride:
            raise ValueError(
                f"bilateral_slice: grid channels {cg} not divisible by "
                f"input channels{' + offset' if has_offset else ''} "
                f"({stride})")
        co = cg // stride
        gx = (jnp.arange(w) + 0.5) * gw / w
        gy = (jnp.arange(h) + 0.5) * gh / h
        gz = guide_v * gd

        def tent(d):
            return jnp.maximum(1.0 - jnp.abs(d), 0.0)

        fx = jnp.floor(gx - 0.5).astype(jnp.int32)
        fy = jnp.floor(gy - 0.5).astype(jnp.int32)
        fz = jnp.floor(gz - 0.5).astype(jnp.int32)

        def per_image(g_img, gz_i, fz_i):
            # g_img [Cg, gd, gh, gw]; gz_i/fz_i [H, W]
            acc = jnp.zeros((cg, gz_i.shape[0], gz_i.shape[1]),
                            g_img.dtype)
            for dx in (0, 1):
                x_ = jnp.clip(fx + dx, 0, gw - 1)          # [W]
                wx = tent(fx + dx + 0.5 - gx)              # [W]
                for dy in (0, 1):
                    y_ = jnp.clip(fy + dy, 0, gh - 1)      # [H]
                    wy = tent(fy + dy + 0.5 - gy)          # [H]
                    plane = g_img[:, :, y_, :][:, :, :, x_]  # [Cg,gd,H,W]
                    for dz in (0, 1):
                        z_ = jnp.clip(fz_i + dz, 0, gd - 1)  # [H, W]
                        wz = tent(fz_i + dz + 0.5 - gz_i)    # [H, W]
                        samp = jnp.take_along_axis(
                            plane, z_[None, None, :, :], axis=1)[:, 0]
                        acc = acc + samp * (wx[None, None, :]
                                            * wy[None, :, None] * wz)
            return acc  # [Cg, H, W]

        coeff = jax.vmap(per_image)(gv, gz, fz)  # [N, Cg, H, W]
        coeff = coeff.reshape(n, co, stride, h, w)
        out = jnp.einsum("nosij,nsij->noij", coeff[:, :, :ci], xv)
        if has_offset:
            out = out + coeff[:, :, ci]
        return out

    return dispatch(f, x, grid, guide)
