"""Detection long-tail operators (RPN/FPN/RCNN/RetinaNet/YOLOv3 families).

Reference: `paddle/fluid/operators/detection/` —
`generate_proposals_op.cc` (+_v2), `distribute_fpn_proposals_op.cc`,
`collect_fpn_proposals_op.cc`, `box_decoder_and_assign_op.cc`,
`retinanet_detection_output_op.cc`, `locality_aware_nms_op.cc`,
`density_prior_box_op.cc`, `yolov3_loss_op.h`, and the top-level
`psroi_pool_op.h` / `prroi_pool_op.h` / `deformable_psroi_pooling_op.h`.

TPU-first design (same stance as vision/ops.py): every op returns
STATIC-shape padded outputs plus valid counts in place of the reference's
variable-length LoD outputs; selection loops become sort + mask
formulations; psroi/prroi pooling are separable mask/integral einsums
that XLA maps onto the VPU/MXU.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import dispatch
from ..core.tensor import Tensor, unwrap
from .ops import _nms_keep_mask, _pairwise_iou

__all__ = [
    "psroi_pool", "prroi_pool", "deformable_psroi_pooling",
    "generate_proposals", "generate_proposals_v2",
    "distribute_fpn_proposals", "collect_fpn_proposals",
    "box_decoder_and_assign", "retinanet_detection_output",
    "locality_aware_nms", "density_prior_box", "yolov3_loss",
    "multiclass_nms2", "multiclass_nms3",
    "target_assign", "mine_hard_examples", "rpn_target_assign",
    "retinanet_target_assign", "polygon_box_transform",
    "generate_proposal_labels", "roi_perspective_transform",
    "generate_mask_labels",
]


# ---------------------------------------------------------------------------
# position-sensitive / precise ROI pooling
# ---------------------------------------------------------------------------
def _roi_batch_ids(rois_num, n_rois):
    ends = jnp.cumsum(rois_num)
    return jnp.sum((jnp.arange(n_rois)[:, None] >= ends[None, :])
                   .astype(jnp.int32), axis=1)


def psroi_pool(x, boxes, boxes_num, output_channels, spatial_scale,
               pooled_height, pooled_width, name=None):
    """Position-sensitive ROI average pooling, exact reference semantics
    (`operators/psroi_pool_op.h`): input channel (c*PH + ph)*PW + pw feeds
    output [c, ph, pw]; integer bin windows floor/ceil'ed from
    round(coord)*scale edges; empty bins -> 0."""
    PH, PW, C = int(pooled_height), int(pooled_width), int(output_channels)

    def f(xv, rois, rois_num):
        n, in_c, h, w = xv.shape
        if in_c != C * PH * PW:
            raise ValueError(
                f"psroi_pool: input channels {in_c} != output_channels*"
                f"pooled_height*pooled_width = {C * PH * PW}")
        batch = _roi_batch_ids(rois_num, rois.shape[0])

        def cround(v):
            return jnp.sign(v) * jnp.floor(jnp.abs(v) + 0.5)

        x1 = cround(rois[:, 0]) * spatial_scale
        y1 = cround(rois[:, 1]) * spatial_scale
        x2 = (cround(rois[:, 2]) + 1.0) * spatial_scale
        y2 = (cround(rois[:, 3]) + 1.0) * spatial_scale
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        bh = rh / PH
        bw = rw / PW
        ph = jnp.arange(PH, dtype=jnp.float32)
        pw = jnp.arange(PW, dtype=jnp.float32)
        hs = jnp.clip(jnp.floor(ph[None, :] * bh[:, None] + y1[:, None]),
                      0, h)
        he = jnp.clip(jnp.ceil((ph[None, :] + 1) * bh[:, None]
                               + y1[:, None]), 0, h)
        ws = jnp.clip(jnp.floor(pw[None, :] * bw[:, None] + x1[:, None]),
                      0, w)
        we = jnp.clip(jnp.ceil((pw[None, :] + 1) * bw[:, None]
                               + x1[:, None]), 0, w)
        hidx = jnp.arange(h, dtype=jnp.float32)
        widx = jnp.arange(w, dtype=jnp.float32)
        rm = ((hidx[None, None, :] >= hs[:, :, None]) &
              (hidx[None, None, :] < he[:, :, None])).astype(xv.dtype)
        cm = ((widx[None, None, :] >= ws[:, :, None]) &
              (widx[None, None, :] < we[:, :, None])).astype(xv.dtype)

        def per_roi(b, rmr, cmr):
            xg = xv[b].reshape(C, PH, PW, h, w)
            sums = jnp.einsum("ih,cijhw,jw->cij", rmr, xg, cmr)
            area = rmr.sum(-1)[:, None] * cmr.sum(-1)[None, :]
            return jnp.where(area > 0, sums / jnp.maximum(area, 1.0), 0.0)

        return jax.vmap(per_roi)(batch, rm, cm)

    return dispatch(f, x, boxes, boxes_num, nondiff=(2,))


def _tri_integral(lo, hi, n):
    """∫_lo^hi max(0, 1-|t-i|) dt for every integer i in [0, n) — the
    exact per-pixel weight of PrRoI pooling's bilinear integral, computed
    from the triangle kernel's antiderivative (separable in x/y)."""
    idx = jnp.arange(n, dtype=jnp.float32)

    def T(t):  # antiderivative of the triangle kernel, T(-1)=0, T(1)=1
        t = jnp.clip(t, -1.0, 1.0)
        return jnp.where(t < 0, (t + 1.0) ** 2 / 2.0,
                         0.5 + t - t * t / 2.0)

    return T(hi[..., None] - idx) - T(lo[..., None] - idx)


def prroi_pool(x, boxes, boxes_num, pooled_height, pooled_width,
               spatial_scale=1.0, name=None):
    """Precise ROI pooling (`operators/prroi_pool_op.h`, PrRoIPooling):
    the EXACT integral of the bilinearly-interpolated feature over each
    continuous bin, divided by the bin area.  Realized in closed form as
    a separable triangle-kernel integral (outer product of 1-D weights),
    so it is fully differentiable w.r.t. both features and coords."""
    PH, PW = int(pooled_height), int(pooled_width)

    def f(xv, rois, rois_num):
        n, c, h, w = xv.shape
        batch = _roi_batch_ids(rois_num, rois.shape[0])
        x1 = rois[:, 0] * spatial_scale
        y1 = rois[:, 1] * spatial_scale
        x2 = rois[:, 2] * spatial_scale
        y2 = rois[:, 3] * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.0)
        rh = jnp.maximum(y2 - y1, 0.0)
        bw = rw / PW
        bh = rh / PH
        ph = jnp.arange(PH, dtype=jnp.float32)
        pw = jnp.arange(PW, dtype=jnp.float32)
        # continuous bin edges [R, P]
        hlo = y1[:, None] + ph[None, :] * bh[:, None]
        hhi = y1[:, None] + (ph[None, :] + 1) * bh[:, None]
        wlo = x1[:, None] + pw[None, :] * bw[:, None]
        whi = x1[:, None] + (pw[None, :] + 1) * bw[:, None]
        gh = _tri_integral(hlo, hhi, h)  # [R, PH, H]
        gw = _tri_integral(wlo, whi, w)  # [R, PW, W]
        area = jnp.maximum(bh[:, None, None] * bw[:, None, None], 1e-9)

        def per_roi(b, ghr, gwr, ar):
            sums = jnp.einsum("ih,chw,jw->cij", ghr, xv[b], gwr)
            return sums / ar

        return jax.vmap(per_roi)(batch, gh, gw, area)

    return dispatch(f, x, boxes, boxes_num, nondiff=(2,))


def deformable_psroi_pooling(x, rois, trans, rois_num=None, no_trans=False,
                             spatial_scale=1.0, output_channels=None,
                             group_size=1, pooled_height=1, pooled_width=1,
                             part_size=None, sample_per_part=4,
                             trans_std=0.1, name=None):
    """Deformable position-sensitive ROI pooling
    (`operators/deformable_psroi_pooling_op.h`): psroi bins shifted by
    learned normalized offsets `trans` [R, 2, part_h, part_w], averaged
    over `sample_per_part`^2 bilinear samples per bin."""
    PH, PW = int(pooled_height), int(pooled_width)
    G = int(group_size)
    S = int(sample_per_part)
    part = int(part_size or PH)

    def f(xv, roi_arr, trans_arr, rois_num_arr):
        n, in_c, h, w = xv.shape
        C = int(output_channels or in_c // (PH * PW))
        R = roi_arr.shape[0]
        batch = _roi_batch_ids(rois_num_arr, R)
        # reference: roi corners offset by 0.5 at scale
        x1 = roi_arr[:, 0] * spatial_scale - 0.5
        y1 = roi_arr[:, 1] * spatial_scale - 0.5
        x2 = (roi_arr[:, 2] + 1.0) * spatial_scale - 0.5
        y2 = (roi_arr[:, 3] + 1.0) * spatial_scale - 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bh = rh / PH
        bw = rw / PW
        sub_h = bh / S
        sub_w = bw / S

        ph = jnp.arange(PH)
        pw = jnp.arange(PW)
        # offset part bin for each output bin
        part_h_idx = jnp.floor(ph.astype(jnp.float32) / PH * part
                               ).astype(jnp.int32)
        part_w_idx = jnp.floor(pw.astype(jnp.float32) / PW * part
                               ).astype(jnp.int32)

        def per_roi(r):
            if no_trans:
                off_h = jnp.zeros((PH, PW))
                off_w = jnp.zeros((PH, PW))
            else:
                t = trans_arr[r]  # [2, part, part]
                off_h = t[0][part_h_idx[:, None], part_w_idx[None, :]] \
                    * trans_std * rh[r]
                off_w = t[1][part_h_idx[:, None], part_w_idx[None, :]] \
                    * trans_std * rw[r]
            # sample grid per bin: [PH, PW, S, S]
            sy = (y1[r] + ph[:, None, None, None] * bh[r] + off_h[:, :, None, None]
                  + (jnp.arange(S)[None, None, :, None] + 0.5) * sub_h[r])
            sx = (x1[r] + pw[None, :, None, None] * bw[r] + off_w[:, :, None, None]
                  + (jnp.arange(S)[None, None, None, :] + 0.5) * sub_w[r])
            valid = (sy > -1.0) & (sy < h) & (sx > -1.0) & (sx < w)
            syc = jnp.clip(sy, 0.0, h - 1.0)
            sxc = jnp.clip(sx, 0.0, w - 1.0)
            y0 = jnp.floor(syc).astype(jnp.int32)
            x0 = jnp.floor(sxc).astype(jnp.int32)
            y1i = jnp.minimum(y0 + 1, h - 1)
            x1i = jnp.minimum(x0 + 1, w - 1)
            ly = syc - y0
            lx = sxc - x0
            img = xv[batch[r]].reshape(C, PH, PW, h, w)
            cidx = jnp.arange(C)

            def gather(yy, xx):
                # img[c, ph, pw, yy[ph,pw,s,s], xx[ph,pw,s,s]]
                return img[
                    cidx[:, None, None, None, None],
                    ph[None, :, None, None, None],
                    pw[None, None, :, None, None],
                    yy[None], xx[None]]

            v = (gather(y0, x0) * ((1 - ly) * (1 - lx))[None]
                 + gather(y0, x1i) * ((1 - ly) * lx)[None]
                 + gather(y1i, x0) * (ly * (1 - lx))[None]
                 + gather(y1i, x1i) * (ly * lx)[None])
            v = jnp.where(valid[None], v, 0.0)
            cnt = jnp.maximum(valid.sum(axis=(-1, -2)), 1)
            return v.sum(axis=(-1, -2)) / cnt[None]

        return jax.vmap(per_roi)(jnp.arange(R))

    rn = rois_num if rois_num is not None else \
        Tensor(jnp.asarray([unwrap(rois).shape[0]], jnp.int32))
    return dispatch(f, x, rois, trans, rn, nondiff=(3,))


# ---------------------------------------------------------------------------
# RPN proposals
# ---------------------------------------------------------------------------
def _decode_proposals(anchors, deltas, variances, offset):
    """generate_proposals' internal BoxCoder (center-size decode with
    per-anchor variances and dw/dh clipped at log(1000/16))."""
    clip = np.log(1000.0 / 16.0)
    aw = anchors[:, 2] - anchors[:, 0] + offset
    ah = anchors[:, 3] - anchors[:, 1] + offset
    acx = anchors[:, 0] + 0.5 * aw
    acy = anchors[:, 1] + 0.5 * ah
    dx, dy, dw, dh = (deltas[:, i] for i in range(4))
    vx, vy, vw, vh = (variances[:, i] for i in range(4))
    cx = acx + dx * vx * aw
    cy = acy + dy * vy * ah
    w = jnp.exp(jnp.minimum(dw * vw, clip)) * aw
    h = jnp.exp(jnp.minimum(dh * vh, clip)) * ah
    return jnp.stack([cx - 0.5 * w, cy - 0.5 * h,
                      cx + 0.5 * w - offset, cy + 0.5 * h - offset],
                     axis=1)


def _proposals_one_image(scores, deltas, anchors, variances, im_h, im_w,
                         scale, pre_nms_top_n, post_nms_top_n, nms_thresh,
                         min_size, offset):
    """[A] scores / [A,4] deltas -> (rois [post,4], probs [post], count)."""
    A = scores.shape[0]
    k = min(pre_nms_top_n if pre_nms_top_n > 0 else A, A)
    top_scores, idx = jax.lax.top_k(scores, k)
    props = _decode_proposals(anchors[idx], deltas[idx], variances[idx],
                              offset)
    # clip to image
    x1 = jnp.clip(props[:, 0], 0, im_w - offset)
    y1 = jnp.clip(props[:, 1], 0, im_h - offset)
    x2 = jnp.clip(props[:, 2], 0, im_w - offset)
    y2 = jnp.clip(props[:, 3], 0, im_h - offset)
    props = jnp.stack([x1, y1, x2, y2], axis=1)
    ms = jnp.maximum(min_size, 1.0) * scale
    ww = props[:, 2] - props[:, 0] + offset
    hh = props[:, 3] - props[:, 1] + offset
    cx = props[:, 0] + ww / 2.0
    cy = props[:, 1] + hh / 2.0
    valid = (ww >= ms) & (hh >= ms) & (cx <= im_w) & (cy <= im_h)
    sc = jnp.where(valid, top_scores, -jnp.inf)
    keep = _nms_keep_mask(props, sc, nms_thresh, box_normalized=False)
    keep = keep & valid
    final = jnp.where(keep, sc, -jnp.inf)
    pk = min(post_nms_top_n, k)
    out_sc, out_idx = jax.lax.top_k(final, pk)
    rois = props[out_idx]
    good = jnp.isfinite(out_sc)
    rois = jnp.where(good[:, None], rois, 0.0)
    probs = jnp.where(good, out_sc, 0.0)
    return rois, probs, good.sum().astype(jnp.int32)


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=True, return_rois_num=True, name=None):
    """RPN proposal generation (`detection/generate_proposals_op.cc`
    ProposalForOneImage): per image top-pre_nms scores -> decode against
    anchors/variances -> clip -> min-size filter -> NMS -> top-post_nms.
    Static shapes: returns ([N, post, 4], [N, post], counts [N]).
    `img_size` rows are [H, W] (v2) or [H, W, scale] (v1 im_info)."""
    off = 1.0 if pixel_offset else 0.0

    def f(sc, deltas, imgs, anc, var):
        n = sc.shape[0]
        a_per = sc.shape[1]
        hw = sc.shape[2] * sc.shape[3]
        anc2 = anc.reshape(-1, 4)
        var2 = var.reshape(-1, 4) if var.ndim > 2 else var
        if var2.shape[0] != anc2.shape[0]:
            var2 = jnp.broadcast_to(var2, anc2.shape)

        def one(i):
            # scores [A,H,W] -> [H*W*A] matching anchors [H,W,A,4] layout
            s = sc[i].transpose(1, 2, 0).reshape(-1)
            d = deltas[i].reshape(a_per, 4, sc.shape[2], sc.shape[3]) \
                .transpose(2, 3, 0, 1).reshape(-1, 4)
            im_h, im_w = imgs[i][0], imgs[i][1]
            scale = imgs[i][2] if imgs.shape[1] > 2 else 1.0
            return _proposals_one_image(
                s, d, anc2, var2, im_h, im_w, scale, pre_nms_top_n,
                post_nms_top_n, nms_thresh, min_size, off)

        rois, probs, counts = jax.vmap(one)(jnp.arange(n))
        return rois, probs, counts

    rois, probs, counts = dispatch(
        f, scores, bbox_deltas, img_size, anchors, variances,
        nondiff=(2,))
    if return_rois_num:
        return rois, probs, counts
    return rois, probs


def generate_proposals_v2(scores, bbox_deltas, img_size, anchors, variances,
                          **kwargs):
    """`generate_proposals_v2` — identical math with im_shape=[H,W] and a
    pixel_offset attr (`detection/generate_proposals_v2_op.cc`)."""
    return generate_proposals(scores, bbox_deltas, img_size, anchors,
                              variances, **kwargs)


# ---------------------------------------------------------------------------
# FPN proposal routing
# ---------------------------------------------------------------------------
def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=True, rois_num=None,
                             name=None):
    """Route each ROI to its FPN level
    (`detection/distribute_fpn_proposals_op.cc`):
    level = floor(log2(sqrt(area)/refer_scale + 1e-6)) + refer_level,
    clipped to [min_level, max_level].  Static shapes: every level output
    is [R, 4] padded (invalid rows zeroed) + per-level counts +
    restore_index [R]."""
    n_levels = max_level - min_level + 1
    off = 1.0 if pixel_offset else 0.0

    def f(rois):
        R = rois.shape[0]
        w = jnp.maximum(rois[:, 2] - rois[:, 0] + off, 0.0)
        h = jnp.maximum(rois[:, 3] - rois[:, 1] + off, 0.0)
        scale = jnp.sqrt(w * h)
        lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-6)) + refer_level
        lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
        outs = []
        counts = []
        order_slots = []
        for L in range(min_level, max_level + 1):
            mask = lvl == L
            # stable pack: indices of this level first, padding after
            key = jnp.where(mask, jnp.arange(R), R + jnp.arange(R))
            perm = jnp.argsort(key)
            packed = jnp.where(mask[perm][:, None], rois[perm], 0.0)
            outs.append(packed)
            counts.append(mask.sum().astype(jnp.int32))
            order_slots.append(perm)
        counts_arr = jnp.stack(counts)
        # restore index: position of each original roi in the
        # concatenated per-level outputs
        starts = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                  jnp.cumsum(counts_arr)[:-1]])
        restore = jnp.zeros(R, jnp.int32)
        for li, L in enumerate(range(min_level, max_level + 1)):
            perm = order_slots[li]
            pos_in_level = jnp.argsort(perm)  # original idx -> packed slot
            restore = jnp.where(lvl == L, starts[li] + pos_in_level,
                                restore)
        return (*outs, counts_arr, restore)

    results = dispatch(f, fpn_rois)
    level_rois = list(results[:n_levels])
    counts = results[n_levels]
    restore = results[n_levels + 1]
    return level_rois, restore, counts


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, rois_num_per_level=None,
                          name=None):
    """Merge per-level ROIs and keep the global top-k by score
    (`detection/collect_fpn_proposals_op.cc`).  Returns
    (rois [post,4], counts scalar).  `rois_num_per_level` (list of scalar
    valid counts, as produced by generate_proposals) distinguishes real
    proposals from the zero-padded rows of the static-shape layout; without
    it, rows with score <= 0 are treated as padding."""
    n_levels = len(multi_rois)

    def f(*arrs):
        rois = jnp.concatenate(arrs[:n_levels], axis=0)
        scores = jnp.concatenate(
            [a.reshape(-1) for a in arrs[n_levels:2 * n_levels]], axis=0)
        if len(arrs) > 2 * n_levels:  # per-level valid counts
            counts = arrs[2 * n_levels:]
            valid = jnp.concatenate([
                jnp.arange(arrs[i].shape[0]) < counts[i]
                for i in range(n_levels)])
        else:
            valid = scores > 0.0
        scores = jnp.where(valid, scores, -jnp.inf)
        top = min(post_nms_top_n, scores.shape[0])
        sc, idx = jax.lax.top_k(scores, top)
        keep = sc > -jnp.inf
        return (jnp.where(keep[:, None], rois[idx], 0.0), sc,
                keep.sum().astype(jnp.int32))

    extra = tuple(rois_num_per_level) if rois_num_per_level is not None \
        else ()
    return dispatch(f, *multi_rois, *multi_scores, *extra)


# ---------------------------------------------------------------------------
# decode+assign / retinanet output / locality-aware NMS
# ---------------------------------------------------------------------------
def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           box_clip=4.135, name=None):
    """`detection/box_decoder_and_assign_op.cc`: decode per-class deltas
    against priors (variance-weighted, dw/dh clipped), then assign each
    ROI the box of its best non-background class."""
    def f(prior, var, target, score):
        R = prior.shape[0]
        n_cls4 = target.shape[1]
        C = n_cls4 // 4
        pw = prior[:, 2] - prior[:, 0] + 1.0
        ph = prior[:, 3] - prior[:, 1] + 1.0
        pcx = prior[:, 0] + 0.5 * pw
        pcy = prior[:, 1] + 0.5 * ph
        t = target.reshape(R, C, 4)
        v = var.reshape(R if var.shape[0] == R else 1, -1)
        v = jnp.broadcast_to(v[:, :4], (R, 4))
        dx = t[..., 0] * v[:, None, 0]
        dy = t[..., 1] * v[:, None, 1]
        dw = jnp.clip(t[..., 2] * v[:, None, 2], -box_clip, box_clip)
        dh = jnp.clip(t[..., 3] * v[:, None, 3], -box_clip, box_clip)
        cx = dx * pw[:, None] + pcx[:, None]
        cy = dy * ph[:, None] + pcy[:, None]
        w = jnp.exp(dw) * pw[:, None]
        h = jnp.exp(dh) * ph[:, None]
        decoded = jnp.stack([cx - 0.5 * w, cy - 0.5 * h,
                             cx + 0.5 * w - 1.0, cy + 0.5 * h - 1.0],
                            axis=-1)  # [R, C, 4]
        # assign: best class EXCLUDING background (last column, reference
        # uses argmax over scores[:-1])
        best = jnp.argmax(score[:, :-1], axis=1)
        assigned = jnp.take_along_axis(
            decoded, best[:, None, None].repeat(4, -1), axis=1)[:, 0]
        return decoded.reshape(R, C * 4), assigned

    return dispatch(f, prior_box, prior_box_var, target_box, box_score)


def locality_aware_nms(bboxes, scores, score_threshold, nms_top_k,
                       keep_top_k, nms_threshold=0.3, normalized=True,
                       nms_eta=1.0, background_label=-1, name=None):
    """`detection/locality_aware_nms_op.cc` (EAST text detection): first
    merge consecutive overlapping boxes by score-weighted averaging, then
    standard multiclass NMS.  Returns ([K, 6] padded, count)."""
    def f(boxes, score):
        C = score.shape[0]
        n = boxes.shape[0]

        def one_class(c):
            sc = score[c]
            # locality merge: weighted-average each box with its
            # IoU>thresh neighbours (single pass, score weights)
            iou = _pairwise_iou(boxes, boxes, normalized)
            wmat = jnp.where(iou > nms_threshold, sc[None, :], 0.0)
            wsum = wmat.sum(1, keepdims=True)
            merged = jnp.where(
                wsum > 0, (wmat @ boxes) / jnp.maximum(wsum, 1e-9), boxes)
            msc = jnp.where(sc >= score_threshold, sc, -jnp.inf)
            k = min(nms_top_k if nms_top_k > 0 else n, n)
            top_sc, idx = jax.lax.top_k(msc, k)
            mb = merged[idx]
            keep = _nms_keep_mask(mb, top_sc, nms_threshold, normalized)
            valid = keep & jnp.isfinite(top_sc)
            return mb, jnp.where(valid, top_sc, -jnp.inf)

        cls_ids = [c for c in range(C) if c != background_label]
        all_boxes = []
        all_scores = []
        all_cls = []
        for c in cls_ids:
            mb, s = one_class(c)
            all_boxes.append(mb)
            all_scores.append(s)
            all_cls.append(jnp.full(s.shape, c, jnp.float32))
        ab = jnp.concatenate(all_boxes)
        asc = jnp.concatenate(all_scores)
        ac = jnp.concatenate(all_cls)
        k = min(keep_top_k if keep_top_k > 0 else asc.shape[0],
                asc.shape[0])
        sc, idx = jax.lax.top_k(asc, k)
        good = jnp.isfinite(sc)
        out = jnp.concatenate([
            ac[idx][:, None], jnp.where(good, sc, 0.0)[:, None],
            jnp.where(good[:, None], ab[idx], 0.0)], axis=1)
        return out, good.sum().astype(jnp.int32)

    return dispatch(f, bboxes, scores)


def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0, name=None):
    """`detection/retinanet_detection_output_op.cc`: per-FPN-level top-k
    + sigmoid-score threshold, decode against anchors (variance-free
    center-size), merge levels, class-wise NMS, global keep_top_k.
    Single-image; returns ([K, 6], count)."""
    def f(*arrs):
        L = (len(arrs) - 1) // 3
        bb = arrs[:L]
        sc = arrs[L:2 * L]
        an = arrs[2 * L:3 * L]
        im = arrs[-1]
        all_boxes = []
        all_scores = []
        for lb, ls, la in zip(bb, sc, an):
            s = ls.reshape(-1, ls.shape[-1])  # [A, C]
            d = lb.reshape(-1, 4)
            a = la.reshape(-1, 4)
            smax = s.max(axis=1)
            k = min(nms_top_k, smax.shape[0])
            _, idx = jax.lax.top_k(smax, k)
            dec = _decode_proposals(
                a[idx], d[idx], jnp.ones((k, 4), jnp.float32), 1.0)
            h_im, w_im, scale = im[0], im[1], im[2]
            dec = jnp.stack([
                jnp.clip(dec[:, 0] / scale, 0, w_im / scale - 1),
                jnp.clip(dec[:, 1] / scale, 0, h_im / scale - 1),
                jnp.clip(dec[:, 2] / scale, 0, w_im / scale - 1),
                jnp.clip(dec[:, 3] / scale, 0, h_im / scale - 1)],
                axis=1)
            all_boxes.append(dec)
            all_scores.append(s[idx])
        boxes = jnp.concatenate(all_boxes)       # [M, 4]
        scores_m = jnp.concatenate(all_scores)   # [M, C]
        C = scores_m.shape[1]
        outs = []
        for c in range(C):
            s = jnp.where(scores_m[:, c] >= score_threshold,
                          scores_m[:, c], -jnp.inf)
            keep = _nms_keep_mask(boxes, s, nms_threshold, False)
            s = jnp.where(keep, s, -jnp.inf)
            outs.append((jnp.full(s.shape, c, jnp.float32), s))
        ac = jnp.concatenate([o[0] for o in outs])
        asc = jnp.concatenate([o[1] for o in outs])
        ab = jnp.tile(boxes, (C, 1))
        k = min(keep_top_k, asc.shape[0])
        top_sc, idx = jax.lax.top_k(asc, k)
        good = jnp.isfinite(top_sc)
        out = jnp.concatenate([
            ac[idx][:, None], jnp.where(good, top_sc, 0.0)[:, None],
            jnp.where(good[:, None], ab[idx], 0.0)], axis=1)
        return out, good.sum().astype(jnp.int32)

    return dispatch(f, *bboxes, *scores, *anchors, im_info)


def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios,
                      variance=(0.1, 0.1, 0.2, 0.2), clip=False,
                      step_w=0.0, step_h=0.0, offset=0.5, flatten_to_2d=False,
                      name=None):
    """`detection/density_prior_box_op.cc` (SSD densified anchors): for
    each (density, fixed_size) pair and each fixed_ratio, lay a density x
    density sub-grid of anchors inside every feature-map cell."""
    def f(feat, img):
        fh, fw = feat.shape[2], feat.shape[3]
        ih, iw = img.shape[2], img.shape[3]
        sw = step_w or iw / fw
        sh = step_h or ih / fh
        boxes = []
        ys, xs = jnp.meshgrid(jnp.arange(fh, dtype=jnp.float32),
                              jnp.arange(fw, dtype=jnp.float32),
                              indexing="ij")
        cx0 = (xs + offset) * sw
        cy0 = (ys + offset) * sh
        for size, dens in zip(fixed_sizes, densities):
            dens = int(dens)
            shift = size / dens
            for ratio in fixed_ratios:
                bw = size * np.sqrt(ratio)
                bh = size / np.sqrt(ratio)
                for di in range(dens):
                    for dj in range(dens):
                        ccx = cx0 - size / 2.0 + shift / 2.0 + dj * shift
                        ccy = cy0 - size / 2.0 + shift / 2.0 + di * shift
                        b = jnp.stack([(ccx - bw / 2.0) / iw,
                                       (ccy - bh / 2.0) / ih,
                                       (ccx + bw / 2.0) / iw,
                                       (ccy + bh / 2.0) / ih], axis=-1)
                        boxes.append(b)
        out = jnp.stack(boxes, axis=2)  # [fh, fw, P, 4]
        if clip:
            out = jnp.clip(out, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                               out.shape)
        if flatten_to_2d:
            return out.reshape(-1, 4), var.reshape(-1, 4)
        return out, var

    return dispatch(f, input, image)


# ---------------------------------------------------------------------------
# YOLOv3 training loss
# ---------------------------------------------------------------------------
def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, scale_x_y=1.0, name=None):
    """`detection/yolov3_loss_op.h` exact semantics: per-cell best-IoU
    ignore mask, per-gt best-anchor positive assignment, sigmoid-CE for
    x/y/objectness/class, L1 for w/h, (2 - w*h) box-size weighting,
    optional label smoothing and mixup scores.  Returns per-image loss
    [N] (plus objectness/match masks like the reference)."""
    an = np.asarray(anchors, np.float32).reshape(-1, 2)
    mask = list(anchor_mask)
    C = int(class_num)
    scale = float(scale_x_y)
    bias = -0.5 * (scale - 1.0)

    def bce(logit, label):
        return jnp.maximum(logit, 0) - logit * label + \
            jnp.log1p(jnp.exp(-jnp.abs(logit)))

    def f(xv, gtb, gtl, *rest):
        gts = rest[0] if rest else None
        n, _, h, w = xv.shape
        m = len(mask)
        b = gtb.shape[1]
        input_size = downsample_ratio * h
        pred = xv.reshape(n, m, 5 + C, h, w)
        tx, ty = pred[:, :, 0], pred[:, :, 1]
        tw, th = pred[:, :, 2], pred[:, :, 3]
        tobj = pred[:, :, 4]
        tcls = pred[:, :, 5:]
        if gts is None:
            gts = jnp.ones((n, b), jnp.float32)
        gt_valid = (gtb[:, :, 2] > 0) & (gtb[:, :, 3] > 0)

        # pred boxes (normalized to input size) for the ignore mask
        gx = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
        gy = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
        aw = jnp.asarray(an[mask, 0])[None, :, None, None]
        ah = jnp.asarray(an[mask, 1])[None, :, None, None]
        px = (gx + jax.nn.sigmoid(tx) * scale + bias) / w
        py = (gy + jax.nn.sigmoid(ty) * scale + bias) / h
        pw = jnp.exp(tw) * aw / input_size
        ph = jnp.exp(th) * ah / input_size

        def iou_centered(x1, y1, w1, h1, x2, y2, w2, h2):
            ox = jnp.clip(jnp.minimum(x1 + w1 / 2, x2 + w2 / 2)
                          - jnp.maximum(x1 - w1 / 2, x2 - w2 / 2), 0)
            oy = jnp.clip(jnp.minimum(y1 + h1 / 2, y2 + h2 / 2)
                          - jnp.maximum(y1 - h1 / 2, y2 - h2 / 2), 0)
            inter = ox * oy
            return inter / jnp.maximum(w1 * h1 + w2 * h2 - inter, 1e-10)

        # best IoU of each predicted box vs any valid gt: [n,m,h,w]
        iou_all = iou_centered(
            px[:, :, :, :, None], py[:, :, :, :, None],
            pw[:, :, :, :, None], ph[:, :, :, :, None],
            gtb[:, None, None, None, :, 0], gtb[:, None, None, None, :, 1],
            gtb[:, None, None, None, :, 2], gtb[:, None, None, None, :, 3])
        iou_all = jnp.where(gt_valid[:, None, None, None, :], iou_all, 0.0)
        best_iou = iou_all.max(axis=-1)
        ignore = best_iou > ignore_thresh

        # per-gt best anchor over ALL anchors (shifted-to-origin IoU)
        a_w = jnp.asarray(an[:, 0]) / input_size
        a_h = jnp.asarray(an[:, 1]) / input_size
        inter = jnp.minimum(gtb[:, :, 2:3], a_w[None, None, :]) * \
            jnp.minimum(gtb[:, :, 3:4], a_h[None, None, :])
        iou_an = inter / (gtb[:, :, 2:3] * gtb[:, :, 3:4]
                          + (a_w * a_h)[None, None, :] - inter + 1e-10)
        best_n = jnp.argmax(iou_an, axis=-1)  # [n, b]
        mask_arr = np.full(an.shape[0], -1, np.int64)
        for mi, a_idx in enumerate(mask):
            mask_arr[a_idx] = mi
        mask_idx = jnp.asarray(mask_arr)[best_n]  # [n,b]; -1 = unmatched
        gi = jnp.clip((gtb[:, :, 0] * w).astype(jnp.int32), 0, w - 1)
        gj = jnp.clip((gtb[:, :, 1] * h).astype(jnp.int32), 0, h - 1)
        matched = (mask_idx >= 0) & gt_valid
        mi_c = jnp.clip(mask_idx, 0, m - 1)

        smooth = min(1.0 / C, 1.0 / 40.0) if use_label_smooth else 0.0
        pos, neg = 1.0 - smooth, smooth

        nb_idx = (jnp.arange(n)[:, None].repeat(b, 1), mi_c, gj, gi)
        t_x = gtb[:, :, 0] * w - gi
        t_y = gtb[:, :, 1] * h - gj
        anw = jnp.asarray(an[:, 0])[best_n]
        anh = jnp.asarray(an[:, 1])[best_n]
        t_w = jnp.log(jnp.maximum(gtb[:, :, 2] * input_size / anw, 1e-9))
        t_h = jnp.log(jnp.maximum(gtb[:, :, 3] * input_size / anh, 1e-9))
        box_scale = (2.0 - gtb[:, :, 2] * gtb[:, :, 3]) * gts

        loc = (bce(tx[nb_idx], t_x) + bce(ty[nb_idx], t_y)
               + jnp.abs(tw[nb_idx] - t_w) + jnp.abs(th[nb_idx] - t_h))
        loc_loss = jnp.where(matched, loc * box_scale, 0.0).sum(axis=1)

        cls_label = jax.nn.one_hot(gtl.astype(jnp.int32), C) * (pos - neg) \
            + neg
        cls_logits = tcls[nb_idx[0], nb_idx[1], :, nb_idx[2], nb_idx[3]]
        cls = bce(cls_logits, cls_label).sum(-1)
        cls_loss = jnp.where(matched, cls * gts, 0.0).sum(axis=1)

        # objectness: positive cells get score, ignored cells skipped
        obj_mask = jnp.zeros((n, m, h, w), jnp.float32)
        obj_mask = jnp.where(ignore, -1.0, obj_mask)
        obj_mask = obj_mask.at[nb_idx].set(
            jnp.where(matched, gts, obj_mask[nb_idx]))
        obj_pos = jnp.where(obj_mask > 1e-5,
                            bce(tobj, 1.0) * obj_mask, 0.0)
        obj_neg = jnp.where((obj_mask <= 1e-5) & (obj_mask > -0.5),
                            bce(tobj, 0.0), 0.0)
        obj_loss = (obj_pos + obj_neg).sum(axis=(1, 2, 3))

        total = loc_loss + cls_loss + obj_loss
        gt_match = jnp.where(matched, mi_c, -1).astype(jnp.int32)
        return total, obj_mask, gt_match

    args = (x, gt_box, gt_label) + ((gt_score,) if gt_score is not None
                                    else ())
    return dispatch(f, *args, nondiff=(1, 2))


# ---------------------------------------------------------------------------
# multiclass_nms versions
# ---------------------------------------------------------------------------
def multiclass_nms2(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                    nms_threshold=0.3, normalized=True, nms_eta=1.0,
                    background_label=0, return_index=False, name=None):
    """`multiclass_nms2` — multiclass_nms that additionally returns the
    selected indices (`detection/multiclass_nms_op.cc` REGISTER v2)."""
    from .ops import multiclass_nms

    out, counts = multiclass_nms(
        bboxes, scores, score_threshold, nms_top_k, keep_top_k,
        nms_threshold=nms_threshold, normalized=normalized,
        nms_eta=nms_eta, background_label=background_label)
    if not return_index:
        return out, counts

    # Recover indices by matching selected boxes back to the inputs.
    # Padded output rows (beyond the valid count) and unmatched rows get
    # index -1; identical input boxes resolve to the first occurrence
    # (documented divergence — the reference tracks provenance through its
    # LoD pipeline).
    def f(sel, boxes, cnt):
        # sel [N,K,6]; boxes [N,M,4] -> index of first exact box match,
        # offset into the flat [N*M] frame like the reference kernel
        # (multiclass_nms_op.cc adds offset = i * num_boxes per image)
        m = boxes.shape[1]
        eq = (jnp.abs(sel[:, :, None, 2:6] - boxes[:, None, :, :])
              < 1e-5).all(-1)
        base = (jnp.arange(sel.shape[0]) * m)[:, None]
        idx = jnp.where(eq.any(-1), jnp.argmax(eq, axis=-1) + base, -1)
        row_valid = (jnp.arange(sel.shape[1])[None, :]
                     < jnp.atleast_1d(cnt)[:, None])
        return jnp.where(row_valid, idx, -1).astype(jnp.int64)

    idx = dispatch(f, out, bboxes, counts, nondiff=(2,))
    return out, counts, idx


def multiclass_nms3(bboxes, scores, rois_num=None, score_threshold=0.05,
                    nms_top_k=1000, keep_top_k=100, nms_threshold=0.3,
                    normalized=True, nms_eta=1.0, background_label=0,
                    return_index=True, name=None):
    """`multiclass_nms3` — v2 plus per-image RoisNum in/out
    (`detection/multiclass_nms_op.cc` REGISTER v3)."""
    res = multiclass_nms2(bboxes, scores, score_threshold, nms_top_k,
                          keep_top_k, nms_threshold=nms_threshold,
                          normalized=normalized, nms_eta=nms_eta,
                          background_label=background_label,
                          return_index=return_index)
    return res


# ---------------------------------------------------------------------------
# train-time target assigners
# ---------------------------------------------------------------------------
def _select_k(order, count, k):
    """First `count` entries of the ranking `order` laid into k slots
    (pad -1).  Works for any order length vs k (gather-clipped), the
    static-shape building block replacing the reference's index-list
    (LoD) outputs."""
    n = order.shape[0]
    slots = jnp.clip(jnp.arange(k), 0, n - 1)
    return jnp.where(jnp.arange(k) < count, jnp.take(order, slots), -1)


def target_assign(x, match_indices, negative_indices=None, mismatch_value=0,
                  name=None):
    """`target_assign` op (`detection/target_assign_op.h`): out[n, p] =
    x[n, match[n, p], p] where matched (match > -1) with weight 1, else
    `mismatch_value` with weight 0.  `negative_indices` (padded [N, Q],
    -1 pad) forces weight 1 / mismatch fill at those prior slots (the
    reference's NegTargetAssign).  x: [N, G, P, K] per-image gt-major
    encoded targets (LoD over N in the reference)."""
    has_neg = negative_indices is not None

    def f(xv, match, *rest):
        n, g, p, k = xv.shape
        m = match.astype(jnp.int32)
        # out[n,p] = xv[n, m[n,p], p] — direct advanced indexing (an
        # [N,P,P,K] take_along_axis intermediate would be O(P^2) memory)
        gathered = xv[jnp.arange(n)[:, None], jnp.clip(m, 0, g - 1),
                      jnp.arange(p)[None, :]]  # [N, P, K]
        matched = (m > -1)[:, :, None]
        out = jnp.where(matched, gathered, float(mismatch_value))
        wt = matched.astype(jnp.float32)
        if has_neg:
            neg = rest[0].astype(jnp.int32)  # [N, Q], -1 padded
            neg_mask = jnp.zeros((n, p), bool)
            valid = neg >= 0
            neg_mask = jnp.zeros((n, p), bool).at[
                jnp.arange(n)[:, None].repeat(neg.shape[1], 1),
                jnp.clip(neg, 0, p - 1)].max(valid)
            out = jnp.where(neg_mask[:, :, None], float(mismatch_value),
                            out)
            wt = jnp.where(neg_mask[:, :, None], 1.0, wt)
        return out, wt

    args = (x, match_indices) + ((negative_indices,) if has_neg else ())
    return dispatch(f, *args, nondiff=tuple(range(1, len(args))))


def mine_hard_examples(cls_loss, match_indices, match_dist, loc_loss=None,
                       neg_pos_ratio=3.0, neg_dist_threshold=0.5,
                       sample_size=0, mining_type="max_negative", name=None):
    """`mine_hard_examples` op (`detection/mine_hard_examples_op.cc`).
    Returns (neg_indices [N, P] padded with -1, neg_count [N],
    updated_match_indices [N, P]).  max_negative: candidates are priors
    with match == -1 and dist < neg_dist_threshold, ranked by cls_loss
    (plus loc_loss for hard_example), capped at neg_pos_ratio * num_pos
    (or sample_size for hard_example)."""
    has_loc = loc_loss is not None
    hard = mining_type == "hard_example"

    def f(cl, match, dist, *rest):
        n, p = cl.shape
        m = match.astype(jnp.int32)
        loss = cl + (rest[0] if has_loc and hard else 0.0)
        if hard:
            # hard_example mining ranks EVERY prior (positives compete for
            # the sample budget too — reference IsEligibleMining)
            eligible = jnp.ones((n, p), bool)
        else:
            eligible = (m == -1) & (dist < neg_dist_threshold)
        # rank eligible priors by loss descending
        key = jnp.where(eligible, loss, -jnp.inf)
        order = jnp.argsort(-key, axis=1)  # [N, P]
        n_cand = eligible.sum(1)
        if hard:
            cap = jnp.minimum(n_cand, sample_size)
        else:
            num_pos = (m != -1).sum(1)
            cap = jnp.minimum(n_cand,
                              (num_pos * neg_pos_ratio).astype(jnp.int32))
        take = jnp.arange(p)[None, :] < cap[:, None]
        sel_mask = jnp.zeros((n, p), bool).at[
            jnp.arange(n)[:, None].repeat(p, 1),
            jnp.clip(order, 0, p - 1)].max(take)
        if hard:
            # selected positives stay matched, unselected get disabled;
            # only selected NEGATIVES go to the neg index list
            upd = jnp.where((m > -1) & ~sel_mask, -1, m)
            neg_mask = sel_mask & (m == -1)
        else:
            upd = m
            neg_mask = sel_mask  # eligibility already implies match == -1
        # reference emits ascending neg index lists
        neg_sorted = jnp.sort(
            jnp.where(neg_mask, jnp.arange(p)[None, :], p), axis=1)
        neg_sorted = jnp.where(neg_sorted == p, -1, neg_sorted)
        return neg_sorted, neg_mask.sum(1).astype(jnp.int32), upd

    args = (cls_loss, match_indices, match_dist) + \
        ((loc_loss,) if has_loc else ())
    return dispatch(f, *args, nondiff=(1, 2))


def _box_to_delta(boxes, anchors):
    """Encode gt boxes relative to anchors (reference BoxToDelta,
    `rpn_target_assign_op.cc` without variance weights)."""
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    ax = anchors[:, 0] + aw * 0.5
    ay = anchors[:, 1] + ah * 0.5
    gw = boxes[:, 2] - boxes[:, 0] + 1.0
    gh = boxes[:, 3] - boxes[:, 1] + 1.0
    gx = boxes[:, 0] + gw * 0.5
    gy = boxes[:, 1] + gh * 0.5
    return jnp.stack([(gx - ax) / aw, (gy - ay) / ah,
                      jnp.log(gw / aw), jnp.log(gh / ah)], axis=1)


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd, im_info, gt_num=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=False, name=None):
    """`rpn_target_assign` (`detection/rpn_target_assign_op.cc`).

    Batched static-shape form: anchor_box [A, 4]; gt_boxes [N, G, 4] with
    `gt_num` [N] valid counts; is_crowd [N, G]; im_info [N, 3]
    (h, w, scale).  Returns (loc_index [N, B], score_index [N, B],
    tgt_label [N, B], tgt_bbox [N, B, 4], bbox_inside_weight [N, B, 4],
    fg_num [N]) with B = rpn_batch_size_per_im; index slots beyond the
    selected count hold -1.  use_random=False follows the reference's
    deterministic take-first-k path; True subsamples with a fixed-seed
    PRNG (stream differs from the reference's minstd_rand)."""
    B = int(rpn_batch_size_per_im)

    def f(anchors, gt, crowd, iminfo, gtn):
        import jax

        a = anchors.shape[0]
        n, g = gt.shape[:2]

        def one(gt_i, crowd_i, info_i, gn_i, key):
            gt_valid = (jnp.arange(g) < gn_i) & (crowd_i == 0)
            if rpn_straddle_thresh >= 0:
                st = rpn_straddle_thresh
                inside = ((anchors[:, 0] >= -st) & (anchors[:, 1] >= -st)
                          & (anchors[:, 2] < info_i[1] + st)
                          & (anchors[:, 3] < info_i[0] + st))
            else:
                inside = jnp.ones((a,), bool)
            iou = _iou_matrix(anchors, gt_i)  # [A, G]
            iou = jnp.where(gt_valid[None, :], iou, 0.0)
            a2g_max = iou.max(1)
            a2g_arg = iou.argmax(1)
            g2a_max = jnp.where(gt_valid, iou.max(0), jnp.inf)
            is_max = ((jnp.abs(iou - g2a_max[None, :]) < 1e-5)
                      & gt_valid[None, :]).any(1)
            has_gt = gt_valid.any()
            fg_cand = inside & has_gt & \
                (is_max | (a2g_max >= rpn_positive_overlap))
            if use_random:
                # random subsample: rank candidates by random key
                rk = jax.random.uniform(key, (a,))
                fg_order = jnp.argsort(jnp.where(fg_cand, rk, 2.0))
            else:
                fg_order = jnp.argsort(
                    jnp.where(fg_cand, jnp.arange(a), a + jnp.arange(a)))
            fg_target = int(rpn_fg_fraction * B)
            fg_count = jnp.minimum(fg_cand.sum(), fg_target)
            fg_sel = _select_k(fg_order, fg_count, B)

            # each anchor gets exactly one label (reference assigns bg
            # first, fg overwrites) — exclude fg candidates from bg
            bg_cand = inside & (a2g_max < rpn_negative_overlap) & ~fg_cand
            bg_allowed = B - fg_count
            if use_random:
                rk2 = jax.random.uniform(jax.random.fold_in(key, 1), (a,))
                bg_order = jnp.argsort(jnp.where(bg_cand, rk2, 2.0))
            else:
                bg_order = jnp.argsort(
                    jnp.where(bg_cand, jnp.arange(a), a + jnp.arange(a)))
            bg_count = jnp.minimum(bg_cand.sum(), bg_allowed)
            bg_sel = _select_k(bg_order, bg_count, B)

            # score slots: fg first then bg (reference concatenates)
            slot = jnp.arange(B)
            shifted_bg = jnp.take(
                bg_sel, jnp.clip(slot - fg_count, 0, B - 1))
            score_index = jnp.where(slot < fg_count, fg_sel, shifted_bg)
            score_index = jnp.where(slot < fg_count + bg_count,
                                    score_index, -1)
            tgt_label = jnp.where(slot < fg_count, 1,
                                  jnp.where(slot < fg_count + bg_count,
                                            0, -1))
            matched_gt = gt_i[a2g_arg[jnp.clip(fg_sel, 0, a - 1)]]
            tgt_bbox = _box_to_delta(
                matched_gt, anchors[jnp.clip(fg_sel, 0, a - 1)])
            w = (fg_sel >= 0)[:, None].astype(jnp.float32)
            return (fg_sel, score_index, tgt_label,
                    jnp.where(w > 0, tgt_bbox, 0.0),
                    jnp.broadcast_to(w, (B, 4)), fg_count.astype(jnp.int32))

        keys = jax.random.split(jax.random.PRNGKey(0), n)
        return jax.vmap(one)(gt, crowd, iminfo, gtn, keys)

    if gt_num is None:
        import numpy as _np

        gt_num = _np.full((int(unwrap(gt_boxes).shape[0]),),
                          int(unwrap(gt_boxes).shape[1]), _np.int32)
    return dispatch(f, anchor_box, gt_boxes, is_crowd, im_info, gt_num,
                    nondiff=(1, 2, 3, 4))


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                            gt_boxes, gt_labels, is_crowd, im_info,
                            gt_num=None, positive_overlap=0.5,
                            negative_overlap=0.4, name=None):
    """`retinanet_target_assign` (`detection/rpn_target_assign_op.cc`
    RetinanetTargetAssign): like rpn_target_assign but without
    subsampling (focal loss uses every anchor), with per-anchor class
    labels from `gt_labels` and a foreground-count output used to
    normalize the focal loss.  Returns (labels [N, A] (-1 ignore,
    0 background, else gt label), tgt_bbox [N, A, 4],
    bbox_inside_weight [N, A, 4], fg_num [N, 1])."""

    def f(anchors, gt, gtl, crowd, iminfo, gtn):
        import jax

        a = anchors.shape[0]
        n, g = gt.shape[:2]

        def one(gt_i, gtl_i, crowd_i, info_i, gn_i):
            gt_valid = (jnp.arange(g) < gn_i) & (crowd_i == 0)
            iou = _iou_matrix(anchors, gt_i)
            iou = jnp.where(gt_valid[None, :], iou, 0.0)
            a2g_max = iou.max(1)
            a2g_arg = iou.argmax(1)
            g2a_max = jnp.where(gt_valid, iou.max(0), jnp.inf)
            is_max = ((jnp.abs(iou - g2a_max[None, :]) < 1e-5)
                      & gt_valid[None, :]).any(1)
            has_gt = gt_valid.any()
            fg = has_gt & (is_max | (a2g_max >= positive_overlap))
            bg = (~fg) & (a2g_max < negative_overlap)
            labels = jnp.where(
                fg, gtl_i[a2g_arg].astype(jnp.int32),
                jnp.where(bg, 0, -1))
            tgt = _box_to_delta(gt_i[a2g_arg], anchors)
            w = fg[:, None].astype(jnp.float32)
            return (labels, jnp.where(w > 0, tgt, 0.0),
                    jnp.broadcast_to(w, (a, 4)),
                    fg.sum().astype(jnp.int32)[None])

        return jax.vmap(one)(gt, gtl, crowd, iminfo, gtn)

    if gt_num is None:
        import numpy as _np

        gt_num = _np.full((int(unwrap(gt_boxes).shape[0]),),
                          int(unwrap(gt_boxes).shape[1]), _np.int32)
    return dispatch(f, anchor_box, gt_boxes, gt_labels, is_crowd, im_info,
                    gt_num, nondiff=(1, 2, 3, 4, 5))


def _iou_matrix(a, b):
    """Pixel-coordinate IoU with the +1 convention the RPN assigners use
    (`detection/bbox_util.h BboxOverlaps`)."""
    aw = (a[:, 2] - a[:, 0] + 1).clip(0)
    ah = (a[:, 3] - a[:, 1] + 1).clip(0)
    bw = (b[:, 2] - b[:, 0] + 1).clip(0)
    bh = (b[:, 3] - b[:, 1] + 1).clip(0)
    ix = (jnp.minimum(a[:, None, 2], b[None, :, 2])
          - jnp.maximum(a[:, None, 0], b[None, :, 0]) + 1).clip(0)
    iy = (jnp.minimum(a[:, None, 3], b[None, :, 3])
          - jnp.maximum(a[:, None, 1], b[None, :, 1]) + 1).clip(0)
    inter = ix * iy
    union = aw[:, None] * ah[:, None] + bw[None, :] * bh[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def polygon_box_transform(x, name=None):
    """EAST-style geometry decode (`detection/polygon_box_transform_op.cc`):
    input [N, 2n, H, W] holds corner offsets; even channels become
    4*w_idx - offset, odd channels 4*h_idx - offset."""

    def f(xv):
        n, c, h, w = xv.shape
        wi = jnp.arange(w, dtype=xv.dtype)[None, None, None, :] * 4.0
        hi = jnp.arange(h, dtype=xv.dtype)[None, None, :, None] * 4.0
        even = (jnp.arange(c) % 2 == 0)[None, :, None, None]
        return jnp.where(even, wi - xv, hi - xv)

    return dispatch(f, x)


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, rois_num=None, gt_num=None,
                             batch_size_per_im=256, fg_fraction=0.25,
                             fg_thresh=0.5, bg_thresh_hi=0.5,
                             bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=81, use_random=False,
                             is_cls_agnostic=False, name=None):
    """RCNN second-stage sampler
    (`detection/generate_proposal_labels_op.cc` SampleRoisForOneImage):
    gt boxes are prepended to the proposals, rois with IoU >= fg_thresh
    become foreground mapped to their best gt, [bg_lo, bg_hi) become
    background (label 0), and the first floor(bs*fg_fraction) fg / rest bg
    are kept (the reference's use_random=False contract).

    Batched static form: rpn_rois [N, R, 4] (+`rois_num` [N]), gt_boxes
    [N, G, 4] (+`gt_num` [N]), gt_classes/is_crowd [N, G].  Returns
    (rois [N, B, 4], labels [N, B] int32 (-1 pad), bbox_targets
    [N, B, 4*class_nums], bbox_inside_weights, bbox_outside_weights,
    counts [N]) with B = batch_size_per_im."""
    B = int(batch_size_per_im)
    W = jnp.asarray(bbox_reg_weights, jnp.float32)

    def f(rois, gtc, crowd, gt, info, rn, gn):
        import jax

        n, r = rois.shape[:2]
        g = gt.shape[1]

        def one(rois_i, gtc_i, crowd_i, gt_i, info_i, gn_i, rn_i):
            # proposals arrive in the scaled frame; gt boxes are in
            # original-image coordinates (reference SampleRoisForOneImage
            # divides rois by im_scale = im_info[2] before mixing them)
            rois_i = rois_i / jnp.maximum(info_i[2], 1e-6)
            all_boxes = jnp.concatenate([gt_i, rois_i], axis=0)  # [G+R,4]
            nb = g + r
            valid = jnp.concatenate([jnp.arange(g) < gn_i,
                                     jnp.arange(r) < rn_i])
            gt_valid = jnp.arange(g) < gn_i
            iou = _iou_matrix(all_boxes, gt_i)
            iou = jnp.where(gt_valid[None, :] & valid[:, None], iou, 0.0)
            max_ov = iou.max(1)
            arg = iou.argmax(1)
            # crowd gt rows are excluded (reference sets overlap -1)
            is_crowd_row = jnp.concatenate(
                [crowd_i != 0, jnp.zeros((r,), bool)])
            max_ov = jnp.where(is_crowd_row, -1.0, max_ov)

            fg_cand = valid & (max_ov >= fg_thresh)
            bg_cand = valid & (max_ov >= bg_thresh_lo) & \
                (max_ov < bg_thresh_hi)
            fg_cap = int(B * fg_fraction)
            fg_order = jnp.argsort(
                jnp.where(fg_cand, jnp.arange(nb), nb + jnp.arange(nb)))
            fg_count = jnp.minimum(fg_cand.sum(), fg_cap)
            fg_sel = _select_k(fg_order, fg_count, B)
            bg_order = jnp.argsort(
                jnp.where(bg_cand, jnp.arange(nb), nb + jnp.arange(nb)))
            bg_count = jnp.minimum(bg_cand.sum(), B - fg_count)
            bg_sel = _select_k(bg_order, bg_count, B)

            slot = jnp.arange(B)
            shifted_bg = jnp.take(bg_sel,
                                  jnp.clip(slot - fg_count, 0, B - 1))
            sel = jnp.where(slot < fg_count, fg_sel, shifted_bg)
            count = fg_count + bg_count
            sel = jnp.where(slot < count, sel, -1)
            sel_c = jnp.clip(sel, 0, nb - 1)

            out_rois = jnp.where((sel >= 0)[:, None],
                                 all_boxes[sel_c], 0.0)
            mapped_gt = arg[sel_c]
            labels = jnp.where(
                slot < fg_count, gtc_i[mapped_gt].astype(jnp.int32),
                jnp.where(slot < count, 0, -1))

            deltas = _box_to_delta(gt_i[mapped_gt],
                                   all_boxes[sel_c]) / W[None, :]
            is_fg = slot < fg_count
            cls = jnp.where(is_cls_agnostic, 1,
                            labels.astype(jnp.int32))
            tgt = jnp.zeros((B, 4 * class_nums), jnp.float32)
            col = jnp.clip(cls, 0, class_nums - 1) * 4
            rows = jnp.arange(B)[:, None]
            cols = col[:, None] + jnp.arange(4)[None, :]
            tgt = tgt.at[rows, cols].set(
                jnp.where(is_fg[:, None], deltas, 0.0))
            w_in = jnp.zeros_like(tgt).at[rows, cols].set(
                jnp.where(is_fg[:, None], 1.0, 0.0))
            return (out_rois, labels, tgt, w_in, w_in,
                    count.astype(jnp.int32))

        return jax.vmap(one)(rois, gtc, crowd, gt, info, gn, rn)

    import numpy as _np

    R = int(unwrap(rpn_rois).shape[1])
    G = int(unwrap(gt_boxes).shape[1])
    N = int(unwrap(rpn_rois).shape[0])
    if rois_num is None:
        rois_num = _np.full((N,), R, _np.int32)
    if gt_num is None:
        gt_num = _np.full((N,), G, _np.int32)
    return dispatch(f, rpn_rois, gt_classes, is_crowd, gt_boxes, im_info,
                    rois_num, gt_num, nondiff=(0, 1, 2, 3, 4, 5, 6))


def roi_perspective_transform(x, rois, transformed_height, transformed_width,
                              spatial_scale=1.0, roi_batch_idx=None,
                              name=None):
    """Perspective-warp quadrilateral ROIs to a fixed size
    (`detection/roi_perspective_transform_op.cc`): per ROI [8] quad
    (x0,y0..x3,y3), build the reference's closed-form quad->rect
    transform (get_transform_matrix, incl. its estimated-width
    normalization), inverse-map each output pixel and bilinearly sample;
    pixels outside the quad or the image get 0 with mask 0.

    Static form: rois [R, 8] + `roi_batch_idx` [R] (image index per ROI;
    the reference's LoD).  Returns (out [R, C, th, tw],
    mask [R, 1, th, tw] int32, transform_matrix [R, 9])."""
    th, tw = int(transformed_height), int(transformed_width)

    def f(xv, rv, bidx):
        n_im, c, h, w = xv.shape
        rx = rv[:, 0::2] * spatial_scale                    # [R, 4]
        ry = rv[:, 1::2] * spatial_scale

        x0, x1, x2, x3 = rx[:, 0], rx[:, 1], rx[:, 2], rx[:, 3]
        y0, y1, y2, y3 = ry[:, 0], ry[:, 1], ry[:, 2], ry[:, 3]
        len1 = jnp.hypot(x0 - x1, y0 - y1)
        len2 = jnp.hypot(x1 - x2, y1 - y2)
        len3 = jnp.hypot(x2 - x3, y2 - y3)
        len4 = jnp.hypot(x3 - x0, y3 - y0)
        est_h = (len2 + len4) / 2.0
        est_w = (len1 + len3) / 2.0
        norm_h = jnp.maximum(2, th)
        norm_w = jnp.round(est_w * (norm_h - 1) /
                           jnp.maximum(est_h, 1e-5)) + 1
        norm_w = jnp.clip(norm_w, 2, tw)

        dx1, dx2, dx3 = x1 - x2, x3 - x2, x0 - x1 + x2 - x3
        dy1, dy2, dy3 = y1 - y2, y3 - y2, y0 - y1 + y2 - y3
        den = dx1 * dy2 - dx2 * dy1 + 1e-5
        m6 = (dx3 * dy2 - dx2 * dy3) / den / (norm_w - 1)
        m7 = (dx1 * dy3 - dx3 * dy1) / den / (norm_h - 1)
        m8 = jnp.ones_like(m6)
        m3 = (y1 - y0 + m6 * (norm_w - 1) * y1) / (norm_w - 1)
        m4 = (y3 - y0 + m7 * (norm_h - 1) * y3) / (norm_h - 1)
        m5 = y0
        m0 = (x1 - x0 + m6 * (norm_w - 1) * x1) / (norm_w - 1)
        m1 = (x3 - x0 + m7 * (norm_h - 1) * x3) / (norm_h - 1)
        m2 = x0
        mat = jnp.stack([m0, m1, m2, m3, m4, m5, m6, m7, m8], -1)  # [R,9]

        ow = jnp.arange(tw, dtype=jnp.float32)
        oh = jnp.arange(th, dtype=jnp.float32)
        gw, gh = jnp.meshgrid(ow, oh)                       # [th, tw]
        u = (m0[:, None, None] * gw + m1[:, None, None] * gh +
             m2[:, None, None])
        v = (m3[:, None, None] * gw + m4[:, None, None] * gh +
             m5[:, None, None])
        ww = (m6[:, None, None] * gw + m7[:, None, None] * gh +
              m8[:, None, None])
        in_w = u / jnp.where(jnp.abs(ww) < 1e-10, 1e-10, ww)
        in_h = v / jnp.where(jnp.abs(ww) < 1e-10, 1e-10, ww)

        # crossing-number in-quad test (reference in_quad)
        def crossings(px, py):
            cnt = jnp.zeros_like(px, jnp.int32)
            for i in range(4):
                xs, ys = rx[:, i], ry[:, i]
                xe, ye = rx[:, (i + 1) % 4], ry[:, (i + 1) % 4]
                xs_, ys_ = xs[:, None, None], ys[:, None, None]
                xe_, ye_ = xe[:, None, None], ye[:, None, None]
                non_horiz = jnp.abs(ys_ - ye_) >= 1e-4
                t = (py - ys_) / jnp.where(non_horiz, ye_ - ys_, 1.0)
                ix = xs_ + t * (xe_ - xs_)
                hit = non_horiz & (t >= 0) & (t < 1) & (ix > px)
                cnt = cnt + hit.astype(jnp.int32)
            return cnt
        inside_quad = (crossings(in_w, in_h) % 2) == 1

        in_bounds = ((in_w > -0.5) & (in_w < w - 0.5) &
                     (in_h > -0.5) & (in_h < h - 0.5))
        valid = inside_quad & in_bounds
        cw = jnp.clip(in_w, 0.0, w - 1.0)
        ch = jnp.clip(in_h, 0.0, h - 1.0)
        wf = jnp.floor(cw)
        hf = jnp.floor(ch)
        wc = jnp.minimum(wf + 1, w - 1)
        hc = jnp.minimum(hf + 1, h - 1)
        lw_ = cw - wf
        lh_ = ch - hf
        img = xv[bidx.astype(jnp.int32)]                    # [R,C,H,W]

        def gat(hh, www):
            return jax.vmap(
                lambda im, hh_, ww_: im[:, hh_, ww_])(
                    img, hh.astype(jnp.int32), www.astype(jnp.int32))
        v00 = gat(hf, wf)
        v01 = gat(hf, wc)
        v10 = gat(hc, wf)
        v11 = gat(hc, wc)
        lw_b = lw_[:, None]
        lh_b = lh_[:, None]
        out = (v00 * (1 - lw_b) * (1 - lh_b) + v01 * lw_b * (1 - lh_b) +
               v10 * (1 - lw_b) * lh_b + v11 * lw_b * lh_b)
        out = jnp.where(valid[:, None], out, 0.0)
        return out, valid[:, None].astype(jnp.int32), mat

    import numpy as _np

    if roi_batch_idx is None:
        roi_batch_idx = _np.zeros((unwrap(rois).shape[0],), _np.int32)
    return dispatch(f, x, rois, roi_batch_idx, nondiff=(1, 2))


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution,
                         gt_num=None, name=None):
    """Mask-RCNN mask-target assigner
    (`detection/generate_mask_labels_op.cc` SampleMaskForOneImage): each
    foreground roi (label > 0) is matched to the non-crowd gt polygon
    whose bounding box overlaps it most, and that polygon is rasterized
    inside the roi at resolution x resolution into the roi's class slot
    (-1 elsewhere).

    Static batched form: gt_segms [N, G, P, V, 2] padded polygon points
    (NaN/repeat-padding tolerated via per-polygon closing), rois
    [N, R, 4], labels_int32 [N, R] (-1 pad).  Fg rois are compacted to
    the front.  Rasterization is pixel-center even-odd point-in-polygon
    (the reference uses COCO's RLE rasterizer; border pixels may differ
    — documented divergence).  Returns (mask_rois [N, R, 4],
    roi_has_mask [N, R] int32 original roi index (-1 pad),
    mask_int32 [N, R, num_classes*resolution^2], fg_counts [N])."""
    res = int(resolution)
    ncls = int(num_classes)

    def one(info, gtc, crowd, segms, rois_i, labels, gn):
        g, p, vmax, _ = segms.shape
        r = rois_i.shape[0]
        im_scale = info[2]
        gt_valid = ((jnp.arange(g) < gn) & (gtc > 0) & (crowd == 0))
        # polygon bboxes -> gt boxes (Poly2Boxes)
        pts = segms.reshape(g, p * vmax, 2)
        finite = jnp.isfinite(pts).all(-1)
        big = jnp.where(finite[..., None], pts, jnp.inf)
        small = jnp.where(finite[..., None], pts, -jnp.inf)
        gt_boxes = jnp.stack([big[:, :, 0].min(1), big[:, :, 1].min(1),
                              small[:, :, 0].max(1),
                              small[:, :, 1].max(1)], -1)   # [G,4]
        fg = labels > 0
        order = jnp.argsort(~fg, stable=True)
        fg_sorted = fg[order]
        rois_s = rois_i[order] / im_scale
        labels_s = labels[order]
        # IoU of fg rois vs gt poly boxes
        x1 = jnp.maximum(rois_s[:, None, 0], gt_boxes[None, :, 0])
        y1 = jnp.maximum(rois_s[:, None, 1], gt_boxes[None, :, 1])
        x2 = jnp.minimum(rois_s[:, None, 2], gt_boxes[None, :, 2])
        y2 = jnp.minimum(rois_s[:, None, 3], gt_boxes[None, :, 3])
        inter = jnp.clip(x2 - x1 + 1, 0) * jnp.clip(y2 - y1 + 1, 0)
        area_r = ((rois_s[:, 2] - rois_s[:, 0] + 1) *
                  (rois_s[:, 3] - rois_s[:, 1] + 1))
        area_g = ((gt_boxes[:, 2] - gt_boxes[:, 0] + 1) *
                  (gt_boxes[:, 3] - gt_boxes[:, 1] + 1))
        iou = inter / jnp.maximum(area_r[:, None] + area_g[None, :] -
                                  inter, 1e-10)
        iou = jnp.where(gt_valid[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)                   # [R]
        # rasterize best_gt's polygons inside each roi
        ys = (jnp.arange(res) + 0.5) / res
        xs = (jnp.arange(res) + 0.5) / res
        gx, gy = jnp.meshgrid(xs, ys)                       # [res,res]
        bw = jnp.maximum(rois_s[:, 2] - rois_s[:, 0], 1e-5)
        bh = jnp.maximum(rois_s[:, 3] - rois_s[:, 1], 1e-5)
        px = rois_s[:, 0, None, None] + gx[None] * bw[:, None, None]
        py = rois_s[:, 1, None, None] + gy[None] * bh[:, None, None]
        polys = segms[best_gt]                              # [R,P,V,2]

        def poly_mask(poly, qx, qy):
            # even-odd crossing count over the poly's finite vertices
            vx, vy = poly[:, 0], poly[:, 1]
            ok = jnp.isfinite(vx) & jnp.isfinite(vy)
            nv = ok.sum()
            idx = jnp.arange(vmax)
            nxt = jnp.where(idx + 1 >= nv, 0, idx + 1)
            xs_, ys_ = vx, vy
            xe_, ye_ = vx[nxt], vy[nxt]
            live = (idx < nv)[:, None, None]
            ysb = ys_[:, None, None]
            yeb = ye_[:, None, None]
            xsb = xs_[:, None, None]
            xeb = xe_[:, None, None]
            non_h = jnp.abs(yeb - ysb) > 1e-12
            t = (qy[None] - ysb) / jnp.where(non_h, yeb - ysb, 1.0)
            ix = xsb + t * (xeb - xsb)
            hit = live & non_h & (t >= 0) & (t < 1) & (ix > qx[None])
            return (hit.sum(0) % 2) == 1

        def roi_mask(pl, qx, qy):
            any_poly = jnp.zeros((res, res), bool)
            for j in range(p):
                any_poly = any_poly | poly_mask(pl[j], qx, qy)
            return any_poly

        masks = jax.vmap(roi_mask)(polys, px, py)           # [R,res,res]
        has_any_gt = gt_valid.any()
        mask_rows = jnp.where(fg_sorted[:, None, None] & has_any_gt,
                              masks, False)
        # expand to class slots (-1 elsewhere / on non-fg rows)
        cls = jnp.clip(labels_s, 0, ncls - 1)               # [R]
        expanded = jnp.full((r, ncls, res * res), -1, jnp.int32)
        expanded = expanded.at[jnp.arange(r), cls].set(
            mask_rows.reshape(r, res * res).astype(jnp.int32))
        expanded = jnp.where(fg_sorted[:, None, None], expanded, -1)
        mask_rois = jnp.where(fg_sorted[:, None], rois_s * im_scale, 0.0)
        roi_has_mask = jnp.where(fg_sorted, order, -1).astype(jnp.int32)
        return (mask_rois, roi_has_mask,
                expanded.reshape(r, ncls * res * res),
                fg.sum().astype(jnp.int32))

    def f(info, gtc, crowd, segms, rois_v, labels, gn):
        return jax.vmap(one)(info, gtc, crowd, segms, rois_v, labels, gn)

    import numpy as _np

    if gt_num is None:
        gt_num = _np.full((unwrap(gt_classes).shape[0],),
                          unwrap(gt_classes).shape[1], _np.int32)
    return dispatch(f, im_info, gt_classes, is_crowd, gt_segms, rois,
                    labels_int32, gt_num,
                    nondiff=(0, 1, 2, 3, 4, 5, 6))
