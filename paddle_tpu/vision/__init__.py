from . import datasets, models, ops, transforms
from .models import LeNet, ResNet, resnet18, resnet34, resnet50, resnet101, resnet152
