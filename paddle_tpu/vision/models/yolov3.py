"""YOLOv3 with a DarkNet-53 backbone — the reference's detection model
family (PaddleDetection-era YOLOv3; ops `yolov3_loss_op.h`,
`yolo_box_op.h`, `multiclass_nms_op.cc` are the kernels it trains and
serves with; cf. the dygraph_to_static darknet test models).

TPU-first: plain Layer composition (convs stay NCHW, XLA lays out for the
MXU), training loss is the exact `yolov3_loss` op over all three scales,
inference decodes with `yolo_box` per scale + one `multiclass_nms` over
the concatenated candidates — all static shapes.
"""
from __future__ import annotations

from ... import nn, ops
from ...nn import functional as F
from ..detection import yolov3_loss
from ..ops import multiclass_nms, yolo_box

__all__ = ["DarkNet53", "YOLOv3", "yolov3_darknet53"]


class ConvBNLayer(nn.Layer):
    def __init__(self, cin, cout, ksize=3, stride=1):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, ksize, stride=stride,
                              padding=(ksize - 1) // 2, bias_attr=False)
        self.bn = nn.BatchNorm2D(cout)
        self.act = nn.LeakyReLU(0.1)

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class BasicBlock(nn.Layer):
    def __init__(self, ch):
        super().__init__()
        self.conv1 = ConvBNLayer(ch, ch // 2, 1)
        self.conv2 = ConvBNLayer(ch // 2, ch, 3)

    def forward(self, x):
        return x + self.conv2(self.conv1(x))


class DarkNet53(nn.Layer):
    """Backbone; returns feature maps at strides 8/16/32 (C3, C4, C5)."""

    DEPTHS = (1, 2, 8, 8, 4)

    def __init__(self):
        super().__init__()
        self.stem = ConvBNLayer(3, 32, 3)
        chans = (64, 128, 256, 512, 1024)
        stages = []
        cin = 32
        for depth, cout in zip(self.DEPTHS, chans):
            blocks = [ConvBNLayer(cin, cout, 3, stride=2)]
            blocks += [BasicBlock(cout) for _ in range(depth)]
            stages.append(nn.Sequential(*blocks))
            cin = cout
        self.stages = nn.LayerList(stages)

    def forward(self, x):
        feats = []
        h = self.stem(x)
        for i, stage in enumerate(self.stages):
            h = stage(h)
            if i >= 2:  # strides 8, 16, 32
                feats.append(h)
        return feats  # [C3, C4, C5]


class YoloDetBlock(nn.Layer):
    def __init__(self, cin, ch):
        super().__init__()
        self.body = nn.Sequential(
            ConvBNLayer(cin, ch, 1), ConvBNLayer(ch, ch * 2, 3),
            ConvBNLayer(ch * 2, ch, 1), ConvBNLayer(ch, ch * 2, 3),
            ConvBNLayer(ch * 2, ch, 1))
        self.tip = ConvBNLayer(ch, ch * 2, 3)

    def forward(self, x):
        route = self.body(x)
        return route, self.tip(route)


class YOLOv3(nn.Layer):
    """Three-scale YOLOv3 head over DarkNet53.

    train:    model(img, gt_box, gt_label) -> scalar loss (sum of the
              three per-scale `yolov3_loss` terms)
    eval:     model(img, im_shape) -> (boxes [N,K,6], counts [N]) after
              per-scale `yolo_box` decode + `multiclass_nms`
    """

    ANCHORS = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45, 59, 119, 116, 90,
               156, 198, 373, 326]
    MASKS = [[6, 7, 8], [3, 4, 5], [0, 1, 2]]  # C5, C4, C3 order

    def __init__(self, num_classes=80, ignore_thresh=0.7,
                 downsamples=(32, 16, 8)):
        super().__init__()
        self.num_classes = num_classes
        self.ignore_thresh = ignore_thresh
        self.downsamples = downsamples
        self.backbone = DarkNet53()
        out_ch = len(self.MASKS[0]) * (5 + num_classes)
        self.blocks = nn.LayerList()
        self.outs = nn.LayerList()
        self.routes = nn.LayerList()
        in_chs = (1024, 768, 384)  # C5, C4+route/2, C3+route/2
        chs = (512, 256, 128)
        for i, (cin, ch) in enumerate(zip(in_chs, chs)):
            self.blocks.append(YoloDetBlock(cin, ch))
            self.outs.append(nn.Conv2D(ch * 2, out_ch, 1))
            if i < 2:
                self.routes.append(ConvBNLayer(ch, ch // 2, 1))

    def _heads(self, img):
        c3, c4, c5 = self.backbone(img)
        outs = []
        feats = [c5, c4, c3]
        route = None
        for i, feat in enumerate(feats):
            if route is not None:
                up = F.interpolate(route, scale_factor=2, mode="nearest")
                feat = ops.concat([up, feat], axis=1)
            route_t, tip = self.blocks[i](feat)
            outs.append(self.outs[i](tip))
            if i < 2:
                route = self.routes[i](route_t)
        return outs  # stride 32, 16, 8

    def forward(self, img, gt_box=None, gt_label=None, im_shape=None,
                score_threshold=0.01, nms_top_k=400, keep_top_k=100,
                nms_threshold=0.45):
        outs = self._heads(img)
        if self.training:
            assert gt_box is not None and gt_label is not None
            total = None
            for i, out in enumerate(outs):
                loss, _, _ = yolov3_loss(
                    out, gt_box, gt_label,
                    anchors=self.ANCHORS,
                    anchor_mask=self.MASKS[i],
                    class_num=self.num_classes,
                    ignore_thresh=self.ignore_thresh,
                    downsample_ratio=self.downsamples[i])
                s = loss.sum()
                total = s if total is None else total + s
            return total
        assert im_shape is not None
        boxes_all, scores_all = [], []
        for i, out in enumerate(outs):
            mask = self.MASKS[i]
            anchors = [self.ANCHORS[2 * m + d] for m in mask
                       for d in (0, 1)]
            boxes, scores = yolo_box(
                out, im_shape, anchors=anchors,
                class_num=self.num_classes, conf_thresh=score_threshold,
                downsample_ratio=self.downsamples[i])
            boxes_all.append(boxes)
            scores_all.append(scores)
        boxes = ops.concat(boxes_all, axis=1)        # [N, M, 4]
        scores = ops.concat(scores_all, axis=1)      # [N, M, C]
        return multiclass_nms(
            boxes, ops.transpose(scores, [0, 2, 1]),
            score_threshold=score_threshold, nms_top_k=nms_top_k,
            keep_top_k=keep_top_k, nms_threshold=nms_threshold,
            background_label=-1)


def yolov3_darknet53(num_classes=80, **kwargs):
    return YOLOv3(num_classes=num_classes, **kwargs)
