from .lenet import LeNet
from .mobilenet import MobileNetV1, MobileNetV2, mobilenet_v1, mobilenet_v2
from .resnet import (BasicBlock, BottleneckBlock, ResNet, resnet18, resnet34,
                     resnet50, resnet101, resnet152, resnext50_32x4d,
                     wide_resnet50_2)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19
from .vit import VisionTransformer, vit_b_16, vit_l_16, vit_s_16
from .yolov3 import DarkNet53, YOLOv3, yolov3_darknet53
