"""Vision Transformer.

BASELINE config 5 names "PP-YOLOE / ViT-L vision" as a perf target; the
reference era ships CNNs in `python/paddle/vision/models/` and the ViT
family in PaddleClas built from the same fluid layers.  This is the
standard ViT (patch-embed conv → [CLS] + position embeddings → pre-norm
Transformer encoder → head), built from paddle_tpu.nn layers so it rides
the same amp/jit/fleet machinery as every other model.

TPU notes: the patch-embed conv is one big stride-P conv (MXU friendly);
everything downstream is dense matmuls at [B, 1+N, D] — no dynamic shapes.
"""
from __future__ import annotations

import numpy as np

from ... import nn

__all__ = ["VisionTransformer", "vit_b_16", "vit_l_16", "vit_s_16"]


class VisionTransformer(nn.Layer):
    def __init__(self, image_size=224, patch_size=16, in_channels=3,
                 num_classes=1000, embed_dim=768, depth=12, num_heads=12,
                 mlp_ratio=4.0, dropout=0.1, attention_dropout=0.0):
        super().__init__()
        assert image_size % patch_size == 0
        self.num_patches = (image_size // patch_size) ** 2
        self.embed_dim = embed_dim
        self.patch_embed = nn.Conv2D(in_channels, embed_dim,
                                     kernel_size=patch_size,
                                     stride=patch_size)
        self.cls_token = self.create_parameter(
            [1, 1, embed_dim],
            default_initializer=nn.initializer.Normal(0.0, 0.02))
        self.pos_embed = self.create_parameter(
            [1, self.num_patches + 1, embed_dim],
            default_initializer=nn.initializer.Normal(0.0, 0.02))
        self.pos_drop = nn.Dropout(dropout)
        layer = nn.TransformerEncoderLayer(
            embed_dim, num_heads, int(embed_dim * mlp_ratio),
            dropout=dropout, activation="gelu",
            attn_dropout=attention_dropout, normalize_before=True)
        self.encoder = nn.TransformerEncoder(layer, depth,
                                             norm=nn.LayerNorm(embed_dim))
        self.head = nn.Linear(embed_dim, num_classes)

    def forward(self, x):
        import paddle_tpu as paddle

        b = x.shape[0]
        patches = self.patch_embed(x)  # [B, D, H/P, W/P]
        patches = patches.flatten(2).transpose([0, 2, 1])  # [B, N, D]
        cls = self.cls_token.expand([b, 1, self.embed_dim])
        seq = paddle.concat([cls, patches], axis=1) + self.pos_embed
        seq = self.pos_drop(seq)
        seq = self.encoder(seq)
        return self.head(seq[:, 0])


def vit_s_16(num_classes=1000, **kw):
    return VisionTransformer(embed_dim=384, depth=12, num_heads=6,
                             num_classes=num_classes, **kw)


def vit_b_16(num_classes=1000, **kw):
    return VisionTransformer(embed_dim=768, depth=12, num_heads=12,
                             num_classes=num_classes, **kw)


def vit_l_16(num_classes=1000, **kw):
    return VisionTransformer(embed_dim=1024, depth=24, num_heads=16,
                             num_classes=num_classes, **kw)
