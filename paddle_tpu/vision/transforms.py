"""Vision transforms (reference `python/paddle/vision/transforms/`)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, dtype=np.float32)
        if arr.max() > 1.5:
            arr = arr / 255.0
        if arr.ndim == 2:
            arr = arr[None] if self.data_format == "CHW" else arr[..., None]
        elif self.data_format == "CHW" and arr.shape[-1] in (1, 3, 4):
            arr = arr.transpose(2, 0, 1)
        return Tensor(arr)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img, dtype=np.float32)
        if self.data_format == "CHW":
            m = self.mean.reshape(-1, 1, 1)
            s = self.std.reshape(-1, 1, 1)
        else:
            m, s = self.mean, self.std
        out = (arr - m) / s
        return Tensor(out) if isinstance(img, Tensor) else out


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def _apply_image(self, img):
        import jax
        import jax.numpy as jnp

        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img, dtype=np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if chw:
            out_shape = (arr.shape[0],) + tuple(self.size)
        elif arr.ndim == 3:
            out_shape = tuple(self.size) + (arr.shape[-1],)
        else:
            out_shape = tuple(self.size)
        out = np.asarray(jax.image.resize(jnp.asarray(arr), out_shape, "bilinear"))
        return Tensor(out) if isinstance(img, Tensor) else out


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
            out = arr[..., ::-1].copy()
            return Tensor(out) if isinstance(img, Tensor) else out
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
            chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
            ax = -2
            out = np.flip(arr, axis=ax).copy()
            return Tensor(out) if isinstance(img, Tensor) else out
        return img


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.padding = padding

    def _apply_image(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        if self.padding:
            p = self.padding
            pads = [(0, 0)] * arr.ndim
            pads[h_ax] = (p, p)
            pads[w_ax] = (p, p)
            arr = np.pad(arr, pads)
        h, w = arr.shape[h_ax], arr.shape[w_ax]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        sl = [slice(None)] * arr.ndim
        sl[h_ax] = slice(i, i + th)
        sl[w_ax] = slice(j, j + tw)
        out = arr[tuple(sl)]
        return Tensor(out) if isinstance(img, Tensor) else out


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def _apply_image(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        h, w = arr.shape[h_ax], arr.shape[w_ax]
        th, tw = self.size
        i = (h - th) // 2
        j = (w - tw) // 2
        sl = [slice(None)] * arr.ndim
        sl[h_ax] = slice(i, i + th)
        sl[w_ax] = slice(j, j + tw)
        out = arr[tuple(sl)]
        return Tensor(out) if isinstance(img, Tensor) else out


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
        out = arr.transpose(self.order)
        return Tensor(out) if isinstance(img, Tensor) else out


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
    out = arr[..., ::-1].copy()
    return Tensor(out) if isinstance(img, Tensor) else out
