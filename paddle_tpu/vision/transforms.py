"""Vision transforms (reference `python/paddle/vision/transforms/`)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, dtype=np.float32)
        if arr.max() > 1.5:
            arr = arr / 255.0
        if arr.ndim == 2:
            arr = arr[None] if self.data_format == "CHW" else arr[..., None]
        elif self.data_format == "CHW" and arr.shape[-1] in (1, 3, 4):
            arr = arr.transpose(2, 0, 1)
        return Tensor(arr)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img, dtype=np.float32)
        if self.data_format == "CHW":
            m = self.mean.reshape(-1, 1, 1)
            s = self.std.reshape(-1, 1, 1)
        else:
            m, s = self.mean, self.std
        out = (arr - m) / s
        return Tensor(out) if isinstance(img, Tensor) else out


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def _apply_image(self, img):
        import jax
        import jax.numpy as jnp

        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img, dtype=np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if chw:
            out_shape = (arr.shape[0],) + tuple(self.size)
        elif arr.ndim == 3:
            out_shape = tuple(self.size) + (arr.shape[-1],)
        else:
            out_shape = tuple(self.size)
        out = np.asarray(jax.image.resize(jnp.asarray(arr), out_shape, "bilinear"))
        return Tensor(out) if isinstance(img, Tensor) else out


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
            out = arr[..., ::-1].copy()
            return Tensor(out) if isinstance(img, Tensor) else out
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
            chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
            ax = -2
            out = np.flip(arr, axis=ax).copy()
            return Tensor(out) if isinstance(img, Tensor) else out
        return img


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.padding = padding

    def _apply_image(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        if self.padding:
            p = self.padding
            pads = [(0, 0)] * arr.ndim
            pads[h_ax] = (p, p)
            pads[w_ax] = (p, p)
            arr = np.pad(arr, pads)
        h, w = arr.shape[h_ax], arr.shape[w_ax]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        sl = [slice(None)] * arr.ndim
        sl[h_ax] = slice(i, i + th)
        sl[w_ax] = slice(j, j + tw)
        out = arr[tuple(sl)]
        return Tensor(out) if isinstance(img, Tensor) else out


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def _apply_image(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        h, w = arr.shape[h_ax], arr.shape[w_ax]
        th, tw = self.size
        i = (h - th) // 2
        j = (w - tw) // 2
        sl = [slice(None)] * arr.ndim
        sl[h_ax] = slice(i, i + th)
        sl[w_ax] = slice(j, j + tw)
        out = arr[tuple(sl)]
        return Tensor(out) if isinstance(img, Tensor) else out


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
        out = arr.transpose(self.order)
        return Tensor(out) if isinstance(img, Tensor) else out


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
    out = arr[..., ::-1].copy()
    return Tensor(out) if isinstance(img, Tensor) else out


def _to_np(img):
    return (img.numpy() if isinstance(img, Tensor)
            else np.asarray(img)), isinstance(img, Tensor)


def _wrap_like(out, was_tensor):
    return Tensor(out) if was_tensor else out


def _layout(arr):
    """-> 'chw' | 'hwc' | '2d' (channel-count based, incl. 1-channel)."""
    if arr.ndim == 2:
        return "2d"
    if arr.ndim == 3 and arr.shape[0] in (1, 3, 4):
        return "chw"
    return "hwc"


def _to_gray(arr, layout):
    """Luma (ITU-R 601-2); returns 2-D [H, W]."""
    w = np.asarray([0.299, 0.587, 0.114], np.float32)
    if layout == "2d":
        return arr
    if layout == "chw":
        return arr[0] if arr.shape[0] == 1 else np.tensordot(
            w, arr[:3], axes=(0, 0))
    return arr[..., 0] if arr.shape[-1] == 1 else arr[..., :3] @ w


class Pad(BaseTransform):
    """reference `transforms.Pad`: constant/edge/reflect border padding,
    numpy-side (host preprocessing)."""

    def __init__(self, padding, fill=0, padding_mode="constant"):
        if isinstance(padding, int):
            padding = (padding, padding, padding, padding)  # l, t, r, b
        elif len(padding) == 2:
            padding = (padding[0], padding[1], padding[0], padding[1])
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        arr, was_t = _to_np(img)
        l, t, r, b = self.padding
        pad = ((0, 0), (t, b), (l, r)) if _layout(arr) == "chw" else \
            ((t, b), (l, r)) + ((0, 0),) * (arr.ndim - 2)
        if self.padding_mode == "constant":
            out = np.pad(arr, pad, constant_values=self.fill)
        else:
            out = np.pad(arr, pad, mode=self.padding_mode)
        return _wrap_like(out, was_t)


class Grayscale(BaseTransform):
    """reference `transforms.Grayscale` (ITU-R 601-2 luma)."""

    def __init__(self, num_output_channels=1):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        arr, was_t = _to_np(img)
        arr = arr.astype(np.float32)
        layout = _layout(arr)
        gray = _to_gray(arr, layout)
        reps = self.num_output_channels
        out = (np.repeat(gray[..., None], reps, -1) if layout == "hwc"
               else np.repeat(gray[None], reps, 0))
        return _wrap_like(out, was_t)


class ColorJitter(BaseTransform):
    """reference `transforms.ColorJitter`: random brightness/contrast/
    saturation scaling (hue omitted: HSV round-trip is a data-pipeline
    nicety, not a capability)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation

    @staticmethod
    def _factor(rng, amount):
        return 1.0 + rng.uniform(-amount, amount) if amount else 1.0

    def _apply_image(self, img):
        arr, was_t = _to_np(img)
        arr = arr.astype(np.float32)
        # value range decided from the INPUT, not the jittered result
        hi = 255.0 if arr.max() > 1.5 else 1.0
        layout = _layout(arr)
        rng = np.random
        arr = arr * self._factor(rng, self.brightness)
        if self.contrast:
            mean = arr.mean()
            arr = (arr - mean) * self._factor(rng, self.contrast) + mean
        if self.saturation and layout != "2d" and \
                (arr.shape[0] if layout == "chw" else arr.shape[-1]) >= 3:
            gray = _to_gray(arr, layout)
            gray = gray[None] if layout == "chw" else gray[..., None]
            f = self._factor(rng, self.saturation)
            arr = arr * f + gray * (1.0 - f)
        return _wrap_like(np.clip(arr, 0.0, hi), was_t)


class RandomResizedCrop(BaseTransform):
    """reference `transforms.RandomResizedCrop`: random area/aspect crop
    then resize (the ImageNet training crop)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self._resize = Resize(self.size, interpolation)

    def _apply_image(self, img):
        arr, _ = _to_np(img)  # Resize below handles Tensor re-wrap inputs
        chw = _layout(arr) == "chw"
        h, w = (arr.shape[1:3]) if chw else arr.shape[:2]
        area = h * w
        rng = np.random
        for _ in range(10):
            target = area * rng.uniform(*self.scale)
            ar = np.exp(rng.uniform(np.log(self.ratio[0]),
                                    np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = rng.randint(0, h - ch + 1)
                j = rng.randint(0, w - cw + 1)
                crop = arr[:, i:i + ch, j:j + cw] if chw else \
                    arr[i:i + ch, j:j + cw]
                return self._resize(crop)
        return self._resize(arr)  # fallback: full image


class RandomRotation(BaseTransform):
    """reference `transforms.RandomRotation`: rotate by a random angle
    (nearest-neighbor resampling about the image center)."""

    def __init__(self, degrees, fill=0):
        if isinstance(degrees, (int, float)):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.fill = fill

    def _apply_image(self, img):
        arr, was_t = _to_np(img)
        angle = np.deg2rad(np.random.uniform(*self.degrees))
        chw = _layout(arr) == "chw"
        h, w = (arr.shape[1:3]) if chw else arr.shape[:2]
        cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
        yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
        # inverse mapping for a COUNTER-clockwise rotation (PIL/paddle
        # convention): source = R(+angle) . (dest - center) + center
        cos, sin = np.cos(angle), np.sin(angle)
        sy = cy + (yy - cy) * cos + (xx - cx) * sin
        sx = cx - (yy - cy) * sin + (xx - cx) * cos
        syi = np.round(sy).astype(np.int64)
        sxi = np.round(sx).astype(np.int64)
        valid = (syi >= 0) & (syi < h) & (sxi >= 0) & (sxi < w)
        syi = np.clip(syi, 0, h - 1)
        sxi = np.clip(sxi, 0, w - 1)
        if chw:
            out = arr[:, syi, sxi]
            out = np.where(valid[None], out, self.fill)
        else:
            out = arr[syi, sxi]
            out = np.where(valid[..., None] if arr.ndim == 3 else valid,
                           out, self.fill)
        return _wrap_like(out.astype(arr.dtype), was_t)


def read_file(path, name=None):
    """Raw file bytes as a uint8 tensor (`operators/read_file_op.cc`)."""
    import numpy as np

    from ..core.tensor import Tensor

    with open(path, "rb") as f:
        data = f.read()
    return Tensor(np.frombuffer(data, np.uint8).copy())


def decode_jpeg(x, mode="unchanged", name=None):
    """JPEG bytes -> CHW uint8 image (`operators/decode_jpeg_op.cc`;
    host-side PIL decode — IO stays on CPU like the reference's
    nvjpeg-less path)."""
    import io

    import numpy as np
    from PIL import Image

    from ..core.tensor import Tensor, unwrap

    raw = bytes(np.asarray(unwrap(x)).astype(np.uint8))
    img = Image.open(io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None, :, :]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(np.ascontiguousarray(arr))


def image_load(path, backend=None):
    """paddle.vision.image_load equivalent (PIL backend)."""
    from PIL import Image

    return Image.open(path)
