"""`paddle.text` — NLP datasets (reference `python/paddle/text/`)."""
from . import datasets  # noqa: F401
from .datasets import (Conll05st, Imdb, Imikolov, Movielens, UCIHousing,
                       WMT14, WMT16)

__all__ = ["datasets", "Conll05st", "Imdb", "Imikolov", "Movielens",
           "UCIHousing", "WMT14", "WMT16"]
