"""Text datasets.

Reference: `python/paddle/text/datasets/` — Imdb (`imdb.py`), Imikolov
(`imikolov.py`), Movielens (`movielens.py`), UCIHousing
(`uci_housing.py`), Conll05st (`conll05.py`), WMT14/WMT16 (`wmt14.py`,
`wmt16.py`).  Each downloads a public corpus, builds a vocabulary, and
yields numpy samples through the `paddle.io.Dataset` protocol.

TPU-image note: this build environment has **zero network egress**, so
every dataset provides a deterministic synthetic corpus (the default when
no file is given) preserving the reference's sample *schema* exactly.
Every dataset ALSO accepts `data_file=` pointing at a pre-downloaded
corpus in the reference's real archive format (Imdb/Imikolov/UCIHousing/
Movielens/WMT14/WMT16/Conll05st each carry their reference parser).
"""
from __future__ import annotations

import gzip
import os
import tarfile
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..io import Dataset

__all__ = ["Imdb", "Imikolov", "Movielens", "UCIHousing", "Conll05st",
           "WMT14", "WMT16"]


from ..utils import stable_rng as _rng  # shared crc32-seeded RandomState


class Imdb(Dataset):
    """Sentiment classification: (word-id sequence, 0/1 label).
    Reference `text/datasets/imdb.py` (aclImdb tar archive)."""

    def __init__(self, data_file: Optional[str] = None, mode="train",
                 cutoff=150, num_samples=512, vocab_size=2000, seq_len=64):
        assert mode in ("train", "test")
        self.mode = mode
        if data_file:
            self._load_archive(data_file, mode, cutoff)
        else:
            r = _rng("imdb", mode)
            self.word_idx = {f"w{i}": i for i in range(vocab_size)}
            n = num_samples
            self.docs = [r.randint(0, vocab_size, (r.randint(8, seq_len),))
                         .astype(np.int64) for _ in range(n)]
            self.labels = [int(l) for l in r.randint(0, 2, (n,))]

    def _load_archive(self, path, mode, cutoff):
        # aclImdb format: tar with {train,test}/{pos,neg}/*.txt
        import re

        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        freq: Dict[str, int] = {}
        raw: List[Tuple[List[str], int]] = []
        with tarfile.open(path) as tf:
            for m in tf.getmembers():
                g = pat.match(m.name)
                if not g:
                    continue
                words = tf.extractfile(m).read().decode(
                    "utf-8", "ignore").lower().split()
                raw.append((words, 1 if g.group(1) == "pos" else 0))
                for w in words:
                    freq[w] = freq.get(w, 0) + 1
        vocab = [w for w, c in sorted(freq.items(), key=lambda kv: -kv[1])
                 if c > cutoff]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        unk = len(self.word_idx)
        self.docs = [np.asarray([self.word_idx.get(w, unk) for w in ws],
                                np.int64) for ws, _ in raw]
        self.labels = [l for _, l in raw]

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, i):
        return self.docs[i], np.asarray(self.labels[i], np.int64)


class Imikolov(Dataset):
    """PTB-style n-gram LM dataset: tuples of n word ids.
    Reference `text/datasets/imikolov.py`."""

    def __init__(self, data_file: Optional[str] = None, data_type="NGRAM",
                 window_size=5, mode="train", min_word_freq=50,
                 num_samples=1024, vocab_size=1000):
        assert data_type in ("NGRAM", "SEQ")
        self.data_type = data_type
        self.window_size = window_size
        if data_file:
            self._load_archive(data_file, mode, min_word_freq)
        else:
            r = _rng("imikolov", mode)
            self.word_idx = {f"w{i}": i for i in range(vocab_size)}
            if data_type == "NGRAM":
                self.data = [tuple(r.randint(0, vocab_size, (window_size,))
                                   .astype(np.int64))
                             for _ in range(num_samples)]
            else:
                self.data = [r.randint(0, vocab_size,
                                       (r.randint(4, 20),)).astype(np.int64)
                             for _ in range(num_samples)]

    def _load_archive(self, path, mode, min_word_freq):
        fn = f"./simple-examples/data/ptb.{ 'train' if mode == 'train' else 'valid' }.txt"
        freq: Dict[str, int] = {}
        lines = []
        with tarfile.open(path) as tf:
            with tf.extractfile(fn) as f:
                for line in f.read().decode().splitlines():
                    ws = line.strip().split()
                    lines.append(ws)
                    for w in ws:
                        freq[w] = freq.get(w, 0) + 1
        vocab = [w for w, c in freq.items() if c >= min_word_freq]
        self.word_idx = {w: i for i, w in enumerate(sorted(vocab))}
        unk = len(self.word_idx)
        self.data = []
        for ws in lines:
            ids = [self.word_idx.get(w, unk) for w in ws]
            if self.data_type == "NGRAM":
                for i in range(len(ids) - self.window_size + 1):
                    self.data.append(tuple(
                        np.asarray(x, np.int64)
                        for x in ids[i: i + self.window_size]))
            else:
                self.data.append(np.asarray(ids, np.int64))

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        return self.data[i]


class Movielens(Dataset):
    """Rating prediction: (user feats..., movie feats..., score).
    Reference `text/datasets/movielens.py` (ml-1m archive)."""

    AGE_TABLE = [1, 18, 25, 35, 45, 50, 56]

    def __init__(self, data_file: Optional[str] = None, mode="train",
                 test_ratio=0.1, rand_seed=0, num_samples=2048,
                 num_users=500, num_movies=300):
        if data_file is not None:
            self._load_archive(data_file, mode.lower(), test_ratio,
                               rand_seed)
            return
        r = _rng("movielens", mode)
        self.num_users = num_users
        self.num_movies = num_movies
        n = num_samples
        self.samples = []
        # same schema as the archive path (and the reference): shape-(1,)
        # scalar features and score = rating*2-5.  Ratings come from a
        # latent-factor model so the corpus is learnable (real preference
        # data has structure; pure noise would make the book recommender
        # example meaningless).
        u_lat = r.randn(num_users, 4)
        m_lat = r.randn(num_movies, 4)
        for _ in range(n):
            user_id = r.randint(0, num_users)
            gender = r.randint(0, 2)
            age = r.randint(0, 7)
            job = r.randint(0, 21)
            movie_id = r.randint(0, num_movies)
            categories = r.randint(0, 2, (18,)).astype(np.int64)
            title = r.randint(0, 5000, (8,)).astype(np.int64)
            affinity = float(u_lat[user_id] @ m_lat[movie_id])
            rating = int(np.clip(round(3 + affinity), 1, 5))
            score = float(rating) * 2 - 5.0
            self.samples.append((
                np.array([user_id], np.int64), np.array([gender], np.int64),
                np.array([age], np.int64), np.array([job], np.int64),
                np.array([movie_id], np.int64), categories, title,
                np.array([score], np.float32)))

    def _load_archive(self, data_file, mode, test_ratio, rand_seed):
        """Parse the real ml-1m zip (reference
        `text/datasets/movielens.py:157-213`): movies.dat / users.dat
        metadata then ratings.dat rows split train/test by rand_seed, each
        sample = ([uid],[gender],[age idx],[job]) + ([mov id],[category
        ids],[title word ids]) + [[rating*2-5]]."""
        import re
        import zipfile

        pattern = re.compile(r"^(.*)\((\d+)\)$")
        movie_info, user_info = {}, {}
        # ids assigned in file-encounter order: deterministic across
        # processes/runs (hash-seeded set iteration would give each DP rank
        # a different vocabulary)
        self.movie_title_dict = {}
        self.categories_dict = {}
        with zipfile.ZipFile(data_file) as pkg:
            with pkg.open("ml-1m/movies.dat") as f:
                for line in f:
                    mid, title, cats = line.decode("latin1").strip() \
                        .split("::")
                    cats = cats.split("|")
                    for c in cats:
                        self.categories_dict.setdefault(
                            c, len(self.categories_dict))
                    m = pattern.match(title)
                    title = m.group(1) if m else title
                    movie_info[int(mid)] = (int(mid), cats, title)
                    for w in title.split():
                        self.movie_title_dict.setdefault(
                            w.lower(), len(self.movie_title_dict))
            with pkg.open("ml-1m/users.dat") as f:
                for line in f:
                    uid, gender, age, job, _ = line.decode("latin1") \
                        .strip().split("::")
                    user_info[int(uid)] = (
                        int(uid), 0 if gender == "M" else 1,
                        self.AGE_TABLE.index(int(age)), int(job))
            rnd = np.random.RandomState(rand_seed)
            is_test = mode == "test"
            self.samples = []
            with pkg.open("ml-1m/ratings.dat") as f:
                for line in f:
                    if (rnd.random_sample() < test_ratio) != is_test:
                        continue
                    uid, mid, rating, _ = line.decode("latin1").strip() \
                        .split("::")
                    u = user_info[int(uid)]
                    mid_i, cats, title = movie_info[int(mid)]
                    self.samples.append((
                        np.array([u[0]], np.int64),
                        np.array([u[1]], np.int64),
                        np.array([u[2]], np.int64),
                        np.array([u[3]], np.int64),
                        np.array([mid_i], np.int64),
                        np.array([self.categories_dict[c] for c in cats],
                                 np.int64),
                        np.array([self.movie_title_dict[w.lower()]
                                  for w in title.split()], np.int64),
                        np.array([float(rating) * 2 - 5.0], np.float32)))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        return self.samples[i]


class UCIHousing(Dataset):
    """Boston housing regression: (13 features, 1 target).
    Reference `text/datasets/uci_housing.py`."""

    FEATURE_DIM = 13

    def __init__(self, data_file: Optional[str] = None, mode="train"):
        if data_file:
            raw = np.loadtxt(data_file)
        else:
            r = _rng("uci_housing", "all")
            w = r.randn(self.FEATURE_DIM)
            x = r.randn(506, self.FEATURE_DIM)
            y = x @ w + 0.1 * r.randn(506)
            raw = np.concatenate([x, y[:, None]], axis=1)
        # normalize features (the reference ships feature-scaled data)
        feats = raw[:, :-1]
        feats = (feats - feats.mean(0)) / (feats.std(0) + 1e-8)
        raw = np.concatenate([feats, raw[:, -1:]], axis=1)
        split = int(len(raw) * 0.8)
        self.data = raw[:split] if mode == "train" else raw[split:]

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        row = self.data[i].astype(np.float32)
        return row[:-1], row[-1:]


class Conll05st(Dataset):
    """SRL dataset: (word_ids, ctx_n2/n1/0/p1/p2, verb_ids, mark, labels).
    Reference `text/datasets/conll05.py`.

    With ``data_file`` (+ the three dictionary files) the REAL CoNLL-2005
    archive format is parsed: a tar containing
    ``conll05st-release/test.wsj/words/test.wsj.words.gz`` (one token per
    line, blank line between sentences) and
    ``.../props/test.wsj.props.gz`` (per-token columns: predicate lemma or
    '-', then one bracketed SRL tag column per predicate).  Each
    (sentence, predicate) pair becomes one sample with the B-/I-/O tag
    expansion and the +-2 verb context windows the reference emits.
    Without files, a deterministic synthetic corpus is generated."""

    UNK_IDX = 0

    def __init__(self, data_file: Optional[str] = None,
                 word_dict_file: Optional[str] = None,
                 verb_dict_file: Optional[str] = None,
                 target_dict_file: Optional[str] = None, mode="train",
                 num_samples=256, vocab_size=5000, num_labels=67,
                 seq_len=24):
        if data_file is not None:
            missing = [n for n, f in [("word_dict_file", word_dict_file),
                                      ("verb_dict_file", verb_dict_file),
                                      ("target_dict_file",
                                       target_dict_file)] if f is None]
            if missing:
                raise ValueError(
                    f"Conll05st with data_file also needs {missing}")
            self.word_dict = self._load_dict(word_dict_file)
            self.predicate_dict = self._load_dict(verb_dict_file)
            self.label_dict = self._load_label_dict(target_dict_file)
            self.samples = self._parse_archive(data_file)
            return
        r = _rng("conll05", mode)
        self.samples = []
        for _ in range(num_samples):
            n = r.randint(5, seq_len)
            words = r.randint(0, vocab_size, (n,)).astype(np.int64)
            ctxs = [r.randint(0, vocab_size, (n,)).astype(np.int64)
                    for _ in range(5)]
            verb = np.full((n,), r.randint(0, vocab_size), np.int64)
            mark = r.randint(0, 2, (n,)).astype(np.int64)
            labels = r.randint(0, num_labels, (n,)).astype(np.int64)
            self.samples.append((words, *ctxs, verb, mark, labels))

    # -- archive parsing (reference conll05.py formats) ---------------------
    @staticmethod
    def _load_dict(filename):
        with open(filename, "r") as f:
            return {line.strip(): i for i, line in enumerate(f)}

    @staticmethod
    def _load_label_dict(filename):
        """targetDict.txt: B-/I- tag names; ids are (B, I) pairs per tag
        in sorted order, then 'O' last (the reference iterates a set —
        sorted here for determinism across runs)."""
        tags = set()
        with open(filename, "r") as f:
            for line in f:
                line = line.strip()
                if line.startswith(("B-", "I-")):
                    tags.add(line[2:])
        d = {}
        for i, tag in enumerate(sorted(tags)):
            d["B-" + tag] = 2 * i
            d["I-" + tag] = 2 * i + 1
        d["O"] = 2 * len(tags)
        return d

    def _parse_archive(self, data_file):
        import gzip
        import tarfile

        with tarfile.open(data_file) as tf:
            base = "conll05st-release/test.wsj"
            wf = tf.extractfile(f"{base}/words/test.wsj.words.gz")
            pf = tf.extractfile(f"{base}/props/test.wsj.props.gz")
            with gzip.GzipFile(fileobj=wf) as words_f, \
                    gzip.GzipFile(fileobj=pf) as props_f:
                word_lines = [ln.decode().strip() for ln in words_f]
                prop_lines = [ln.decode().strip().split()
                              for ln in props_f]
        samples = []
        sent: List[str] = []
        rows: List[List[str]] = []
        for word, row in zip(word_lines + [""], prop_lines + [[]]):
            if row:
                sent.append(word)
                rows.append(row)
                continue
            if sent:
                # column 0: predicate lemmas ('-' elsewhere); columns
                # 1..: one bracketed tag sequence per predicate
                verbs = [r[0] for r in rows if r[0] != "-"]
                n_preds = len(rows[0]) - 1
                for p in range(n_preds):
                    tags = self._expand_tags([r[p + 1] for r in rows])
                    samples.append(
                        self._make_sample(sent, verbs[p], tags))
            sent, rows = [], []
        return samples

    @staticmethod
    def _expand_tags(col):
        """Bracketed props column -> B-/I-/O sequence (the reference's
        in-bracket state machine)."""
        out = []
        cur, inside = "O", False
        for tok in col:
            if tok == "*":
                out.append("I-" + cur if inside else "O")
            elif tok == "*)":
                out.append("I-" + cur)
                inside = False
            elif "(" in tok and ")" in tok:
                cur = tok[1:tok.find("*")]
                out.append("B-" + cur)
                inside = False
            elif "(" in tok:
                cur = tok[1:tok.find("*")]
                out.append("B-" + cur)
                inside = True
            else:
                raise RuntimeError(f"unexpected SRL tag {tok!r}")
        return out

    def _make_sample(self, sentence, predicate, labels):
        n = len(sentence)
        v = labels.index("B-V")
        mark = [0] * n
        ctx = {}
        for off, key, pad in ((-2, "n2", "bos"), (-1, "n1", "bos"),
                              (0, "0", None), (1, "p1", "eos"),
                              (2, "p2", "eos")):
            j = v + off
            if 0 <= j < n:
                mark[j] = 1
                ctx[key] = sentence[j]
            else:
                ctx[key] = pad
        wd, UNK = self.word_dict, self.UNK_IDX
        word_idx = np.array([wd.get(w, UNK) for w in sentence], np.int64)
        ctxs = [np.full((n,), wd.get(ctx[k], UNK), np.int64)
                for k in ("n2", "n1", "0", "p1", "p2")]
        pred = np.full((n,), self.predicate_dict.get(predicate, 0),
                       np.int64)
        lab = np.array([self.label_dict[w] for w in labels], np.int64)
        return (word_idx, *ctxs, pred,
                np.array(mark, np.int64), lab)

    def get_dict(self):
        """reference `Conll05st.get_dict`."""
        return self.word_dict, self.predicate_dict, self.label_dict

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        return self.samples[i]


class _WMTBase(Dataset):
    """(source ids, target ids, target-next ids) translation triples."""

    BOS, EOS, UNK = 0, 1, 2
    START_MARK, END_MARK, UNK_MARK = "<s>", "<e>", "<unk>"

    def __init__(self, name, mode, dict_size, num_samples, seq_len):
        r = _rng(name, mode)
        dict_size = max(dict_size, 16)
        self.src_dict = {f"s{i}": i for i in range(dict_size)}
        self.trg_dict = {f"t{i}": i for i in range(dict_size)}
        self.samples = []
        for _ in range(num_samples):
            ns = r.randint(3, seq_len)
            nt = r.randint(3, seq_len)
            src = r.randint(3, dict_size, (ns,)).astype(np.int64)
            trg_body = r.randint(3, dict_size, (nt,)).astype(np.int64)
            trg = np.concatenate([[self.BOS], trg_body]).astype(np.int64)
            trg_next = np.concatenate([trg_body, [self.EOS]]).astype(np.int64)
            self.samples.append((src, trg, trg_next))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        return self.samples[i]


class WMT14(_WMTBase):
    """reference `text/datasets/wmt14.py`."""

    def __init__(self, data_file: Optional[str] = None, mode="train",
                 dict_size=1000, num_samples=512, seq_len=20):
        if data_file is not None:
            self._load_archive(data_file, mode.lower(), dict_size)
            return
        super().__init__("wmt14", mode, dict_size, num_samples, seq_len)

    def _load_archive(self, data_file, mode, dict_size):
        """Parse the real wmt14 tgz (reference `wmt14.py:108-161`):
        *src.dict / *trg.dict members give the vocabularies (first
        `dict_size` lines), the `{mode}/{mode}` member holds tab-separated
        src/trg sentence pairs; pairs longer than 80 tokens are dropped."""
        import tarfile

        def to_dict(fd, size):
            out = {}
            for i, line in enumerate(fd):
                if i >= size:
                    break
                out[line.decode("utf-8").strip()] = i
            return out

        with tarfile.open(data_file, mode="r") as f:
            members = f.getmembers()
            src_name = [m for m in members if m.name.endswith("src.dict")]
            trg_name = [m for m in members if m.name.endswith("trg.dict")]
            assert len(src_name) == 1 and len(trg_name) == 1
            self.src_dict = to_dict(f.extractfile(src_name[0]), dict_size)
            self.trg_dict = to_dict(f.extractfile(trg_name[0]), dict_size)
            suffix = "{}/{}".format(mode, mode)
            self.samples = []
            for m in members:
                if not m.name.endswith(suffix):
                    continue
                for line in f.extractfile(m):
                    parts = line.decode("utf-8").strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src = [self.src_dict.get(w, self.UNK) for w in
                           [self.START_MARK] + parts[0].split()
                           + [self.END_MARK]]
                    trg = [self.trg_dict.get(w, self.UNK)
                           for w in parts[1].split()]
                    if len(src) > 80 or len(trg) > 80:
                        continue
                    trg_next = trg + [self.trg_dict[self.END_MARK]]
                    trg = [self.trg_dict[self.START_MARK]] + trg
                    self.samples.append(
                        (np.asarray(src, np.int64),
                         np.asarray(trg, np.int64),
                         np.asarray(trg_next, np.int64)))


class WMT16(_WMTBase):
    """reference `text/datasets/wmt16.py`."""

    def __init__(self, data_file: Optional[str] = None, mode="train",
                 src_lang_dict_size=1000, trg_lang_dict_size=1000,
                 lang="en", num_samples=512, seq_len=20):
        if data_file is not None:
            self._load_archive(data_file, mode.lower(), lang,
                               src_lang_dict_size, trg_lang_dict_size)
            return
        super().__init__("wmt16", mode,
                         max(src_lang_dict_size, trg_lang_dict_size),
                         num_samples, seq_len)

    def _load_archive(self, data_file, mode, lang, src_size, trg_size):
        """Parse the real wmt16 tgz (reference `wmt16.py:159-215`): vocab
        built from the `wmt16/train` member per language (marks first, then
        words by frequency), data from `wmt16/{mode}` tab-separated en/de
        pairs with `lang` choosing the source column."""
        import tarfile
        from collections import defaultdict

        src_col = 0 if lang == "en" else 1
        with tarfile.open(data_file, mode="r") as f:
            # one pass over wmt16/train fills BOTH frequency tables (a
            # gzip tar re-decompresses the member on every extractfile)
            freq = (defaultdict(int), defaultdict(int))
            for line in f.extractfile("wmt16/train"):
                parts = line.decode("utf-8").strip().split("\t")
                if len(parts) != 2:
                    continue
                for col in (0, 1):
                    for w in parts[col].split():
                        freq[col][w] += 1

            def to_dict(col, size):
                words = [self.START_MARK, self.END_MARK, self.UNK_MARK]
                words += [w for w, _ in sorted(freq[col].items(),
                                               key=lambda x: x[1],
                                               reverse=True)
                          ][:max(0, size - 3)]
                return {w: i for i, w in enumerate(words)}

            self.src_dict = to_dict(src_col, src_size)
            self.trg_dict = to_dict(1 - src_col, trg_size)
            self.samples = []
            for line in f.extractfile("wmt16/{}".format(mode)):
                parts = line.decode("utf-8").strip().split("\t")
                if len(parts) != 2:
                    continue
                src = [self.BOS] + [self.src_dict.get(w, self.UNK)
                                    for w in parts[src_col].split()] \
                    + [self.EOS]
                trg = [self.trg_dict.get(w, self.UNK)
                       for w in parts[1 - src_col].split()]
                trg_next = trg + [self.EOS]
                trg = [self.BOS] + trg
                self.samples.append((np.asarray(src, np.int64),
                                     np.asarray(trg, np.int64),
                                     np.asarray(trg_next, np.int64)))
