"""Fleet serving: the network front door over N engine replicas.

Everything below the fleet layer shipped engine-by-engine in PRs 6-17
— content-addressed prefix pages, the write-ahead journal, `/readyz`
as an admission key, calibrated step-cost headroom — but nothing
polled them from OUTSIDE the process.  This package is that consumer,
in two halves:

* `fleet.edge.EdgeServer` — a real HTTP edge on one replica: a stdlib
  daemon-thread server (the `observability.opsserver` pattern) that
  wraps the engine in a `ServingFrontend`, accepts generation requests
  over ``POST /v1/generate`` and streams tokens back as Server-Sent
  Events, serves the failover surfaces (``/v1/adopt`` replays a dead
  sibling's journal into this replica, ``/v1/resume`` reconnects a
  migrated stream), and describes itself on ``GET /v1/info``;

* `fleet.router.FleetRouter` — the fleet brain: routes each request by
  **prefix affinity** (the PR 6 chain hashes of the longest
  page-aligned prompt prefix are the routing key, so requests sharing
  a prefix land on the replica already holding those KV pages), admits
  by the ops plane's ``/readyz`` verdict + capacity headroom +
  predicted step cost rather than a raw slot count, and on replica
  death performs **zero-loss failover**: the dead replica's journal
  replays into a survivor (`durability.adopt_from_dir`) and every
  interrupted SSE stream resumes mid-generation, token-for-token.

See docs/FLEET.md for the routing key, the admission predicate, and a
failover walkthrough; tools/bench_fleet.py is the chaos bench that
pins the zero-loss + continuity contract.
"""
from .edge import EdgeServer
from .router import (FleetConfigError, FleetRouter, FleetStream,
                     ReplicaHandle)

__all__ = ["EdgeServer", "FleetRouter", "FleetStream", "ReplicaHandle",
           "FleetConfigError"]
