"""The fleet router: prefix-affinity placement, predicted-cost
admission, and zero-loss failover over N `fleet.edge.EdgeServer`
replicas.

**Routing key** — the PR 6 content-addressed page chain hashes: the
router hashes the longest page-aligned prefix of each prompt with the
replicas' shared model salt (``/v1/info`` exposes salt + page size, so
the router's digests are byte-identical to the ones the engines'
`_probe_prefix` matches against).  A request whose longest hash maps
to an admissible replica is an **affinity hit** — it lands on the
replica already holding those KV pages; everything else places
least-loaded and claims the affinity map for its prefix chain.

**Admission predicate** — a replica takes new work iff its ops plane's
``/readyz`` verdict holds (serving AND headroom > 0 AND no
page-severity alert AND no watchdog-overdue step; see
`observability.opsserver.engine_ready`).  When the cost observatory is
armed the same poll carries ``predicted_step_s``/``slo_ok``, and the
router prefers replicas whose predicted next step still meets the SLO
ceiling — admission by predicted cost, not by a raw slot count.

**Failover** — a replica that stops answering (its streams break, its
``/readyz`` refuses) is declared dead after ``dead_after`` consecutive
failures.  The router then picks a survivor, reports exactly how many
tokens each interrupted stream actually delivered, and calls the
survivor's ``/v1/adopt`` — `durability.adopt_from_dir` replays the
dead replica's journal into the survivor's LIVE engine.  Every
interrupted `FleetStream` reconnects through ``/v1/resume`` and
continues token-for-token: delivered tokens are never re-sent (the
emit gate), journaled-but-undelivered tokens re-deliver (snapshot
backfill or live recompute).  tools/bench_fleet.py kill-9s a replica
under load and pins zero loss + greedy continuity on exactly this
path.
"""
from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from collections import OrderedDict, deque
from typing import Dict, List, Optional

from .. import observability as _obs
from ..inference.serving import _chain_hash
from ..observability import fleettrace

__all__ = ["FleetConfigError", "ReplicaHandle", "FleetStream",
           "FleetRouter"]


class FleetConfigError(ValueError):
    """A fleet wiring mistake that must fail construction loudly
    (e.g. a replica with no ops plane: the router cannot admit what it
    cannot poll)."""


def _get_json(url: str, timeout: float) -> dict:
    """GET a JSON document; non-2xx responses that still carry JSON
    (``/readyz`` serves 503 with the full verdict) parse instead of
    raising."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return json.loads(e.read())


def _post_json(url: str, body: dict, timeout: float) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError as e:
        raise RuntimeError(
            f"POST {url} -> {e.code}: {e.read()[:300]!r}") from None


def _sse_events(resp):
    """Parse ``data: <json>`` Server-Sent Events off a streaming HTTP
    response until EOF."""
    for raw in resp:
        line = raw.strip()
        if line.startswith(b"data: "):
            yield json.loads(line[6:])


class ReplicaHandle:
    """The router's view of one replica: its edge + ops URLs, the
    cached ``/v1/info`` identity, and the latest ``/readyz`` poll."""

    def __init__(self, name: str, edge_url: str,
                 ops_url: Optional[str] = None,
                 journal_dir: Optional[str] = None,
                 timeout_s: float = 5.0):
        self.name = str(name)
        self.edge_url = edge_url.rstrip("/")
        self.ops_url = ops_url.rstrip("/") if ops_url else None
        # the journal path AS THE ROUTER (and a survivor) reaches it —
        # defaults to the path the replica self-reports, overridable
        # for setups where mounts differ
        self.journal_dir = journal_dir
        self.timeout_s = float(timeout_s)
        self.info: dict = {}
        # poll state
        self.alive = True
        self.ready = False
        self.headroom = 0
        # measured /readyz round-trip (the router's only per-replica
        # latency signal) + the NTP-style clock-offset estimator the
        # fleet trace merge maps replica timestamps with
        self.poll_rtt_s: Optional[float] = None
        self._clock = fleettrace.ClockSync()
        self.slo_ok: Optional[bool] = None
        self.predicted_step_s: Optional[float] = None
        self.failures = 0          # consecutive poll failures
        self.assigned_since_poll = 0
        # failover state
        self.failed_over = False
        self.migration: Optional[tuple] = None  # (survivor, migrated)
        self.fo_event = threading.Event()
        self._fo_lock = threading.Lock()

    # -- HTTP ----------------------------------------------------------------
    def fetch_info(self) -> dict:
        self.info = _get_json(self.edge_url + "/v1/info",
                              self.timeout_s)
        if self.journal_dir is None:
            j = self.info.get("journal") or {}
            self.journal_dir = j.get("dir")
        return self.info

    def poll(self) -> bool:
        """One ``/readyz`` round; returns the ready bit.  A poll that
        cannot reach the replica counts a consecutive failure (the
        death detector's input) and reads not-ready.  The measured
        round-trip lands on `paddle_fleet_poll_rtt_seconds{replica}`;
        when the replica reports its own clock (``now_ns``, served
        with FLAGS_fleet_trace on) the same handshake feeds the
        NTP-style offset estimate the trace merge uses."""
        t0 = _obs.now_ns()
        try:
            doc = _get_json(self.ops_url + "/readyz", self.timeout_s)
        except Exception:
            self.failures += 1
            self.ready = False
            return False
        t1 = _obs.now_ns()
        self.poll_rtt_s = (t1 - t0) / 1e9
        _obs.FLEET_POLL_RTT.set(self.poll_rtt_s, replica=self.name)
        server_ns = doc.get("now_ns")
        if server_ns is not None:
            self._clock.observe(self.name, t0, t1, int(server_ns))
        self.failures = 0
        engines = doc.get("engines") or {}
        crit = engines.get(str(self.info.get("engine_id")))
        if crit is None and len(engines) == 1:
            # the replica recovered in-process onto a new engine
            # generation: follow it
            crit = next(iter(engines.values()))
        if crit is None:
            self.ready = False
            return False
        self.ready = bool(crit.get("ready"))
        self.headroom = int(crit.get("headroom_slots") or 0)
        self.slo_ok = crit.get("slo_ok")
        self.predicted_step_s = crit.get("predicted_step_s")
        self.assigned_since_poll = 0
        return self.ready

    def clock_offset_ns(self) -> int:
        """Estimated replica-clock minus router-clock (0 until the
        replica reports ``now_ns`` on a poll)."""
        return self._clock.offset_ns(self.name)

    def admissible(self) -> bool:
        """May the router place NEW work here right now?  The /readyz
        verdict plus the router's own not-yet-polled assignments
        (headroom is a snapshot; work placed since then consumes
        it)."""
        return self.alive and self.ready and \
            self.headroom - self.assigned_since_poll > 0

    def generate(self, prompt_ids, max_new_tokens: int, kwargs: dict,
                 timeout_s: float = 600.0,
                 trace: Optional[str] = None):
        """Open one streaming generation; returns ``(resp, meta)`` —
        the live SSE response plus its already-parsed meta event.
        ``trace`` (a fleet trace id) rides the ``x-paddle-trace``
        header; None sends the pre-trace wire format byte for byte."""
        body = {"prompt_ids": list(prompt_ids),
                "max_new_tokens": int(max_new_tokens), **kwargs}
        headers = {"Content-Type": "application/json"}
        if trace is not None:
            headers[fleettrace.TRACE_HEADER] = str(trace)
        req = urllib.request.Request(
            self.edge_url + "/v1/generate",
            data=json.dumps(body).encode(),
            headers=headers, method="POST")
        resp = urllib.request.urlopen(req, timeout=timeout_s)
        if resp.status != 200:
            raise RuntimeError(
                f"{self.name}: /v1/generate -> {resp.status}")
        meta = next(_sse_events(resp))
        return resp, meta

    def adopt(self, journal_dir: str, delivered: Dict[int, int],
              traces: Optional[Dict[int, str]] = None) -> dict:
        body = {"journal_dir": journal_dir, "delivered": delivered}
        if traces:
            # donor id -> trace id: the adopting edge's fallback for
            # journals written before FLAGS_fleet_trace was flipped
            body["traces"] = {str(k): str(v)
                              for k, v in traces.items()}
        out = _post_json(self.edge_url + "/v1/adopt", body,
                         timeout_s := max(self.timeout_s, 60.0))
        return out["migrated"]

    def resume(self, donor_id: int, timeout_s: float = 600.0,
               trace: Optional[str] = None):
        url = self.edge_url + f"/v1/resume?request={int(donor_id)}"
        if trace is not None:
            req = urllib.request.Request(
                url, headers={fleettrace.TRACE_HEADER: str(trace)})
            resp = urllib.request.urlopen(req, timeout=timeout_s)
        else:
            resp = urllib.request.urlopen(url, timeout=timeout_s)
        meta = next(_sse_events(resp))
        return resp, meta

    def alertz(self) -> Optional[dict]:
        try:
            return _get_json(self.ops_url + "/alertz", self.timeout_s)
        except Exception:
            return None


class FleetStream:
    """One request's life through the fleet: routed, streamed, and —
    when its replica dies mid-generation — resumed on the survivor.
    A dedicated reader thread drains the SSE stream; consumers either
    iterate (blocking per token) or call `result()` for the final
    token list.  ``tokens`` only ever grows with DELIVERED tokens, so
    ``len(tokens)`` is exactly the count the router reports into a
    failover's ``delivered`` map."""

    _DONE = object()

    def __init__(self, router: "FleetRouter", prompt_ids,
                 max_new_tokens: int, kwargs: dict,
                 trace_id: Optional[str] = None):
        self.router = router
        self.prompt_ids = list(prompt_ids)
        self.max_new_tokens = int(max_new_tokens)
        self.kwargs = dict(kwargs)
        # fleet trace id (observability.fleettrace): minted by
        # FleetRouter.submit while FLAGS_fleet_trace is on; every leg
        # — including post-failover resume — carries the SAME id
        self.trace_id = trace_id
        self.tokens: List[int] = []
        self.finish_reason: Optional[str] = None
        self.error: Optional[BaseException] = None
        self.replica: Optional[str] = None   # current replica name
        self.remote_id: Optional[int] = None
        self.affinity_hit: Optional[bool] = None
        self.failovers = 0
        self.t_submit = time.perf_counter()
        self.ttft_s: Optional[float] = None
        self._q: "deque" = deque()
        self._cv = threading.Condition()
        self._done = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="fleet-stream", daemon=True)
        self._thread.start()

    # -- consumer side -------------------------------------------------------
    def __iter__(self):
        i = 0
        while True:
            with self._cv:
                while i >= len(self.tokens) and not self._done.is_set():
                    self._cv.wait(timeout=1.0)
                if i < len(self.tokens):
                    tok = self.tokens[i]
                else:
                    return
            yield tok
            i += 1

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Wait for completion; returns the full token list.  Raises
        the stream's terminal error, if any."""
        if not self._done.wait(timeout=timeout):
            raise TimeoutError(
                f"fleet stream incomplete after {timeout}s "
                f"({len(self.tokens)} tokens, replica {self.replica})")
        if self.error is not None:
            raise self.error
        return list(self.tokens)

    @property
    def done(self) -> bool:
        return self._done.is_set()

    # -- reader side ---------------------------------------------------------
    def _deliver(self, tok: int):
        with self._cv:
            if not self.tokens and self.ttft_s is None:
                self.ttft_s = time.perf_counter() - self.t_submit
            self.tokens.append(int(tok))
            self._cv.notify_all()

    def _finish(self, reason: Optional[str],
                error: Optional[BaseException] = None):
        self.finish_reason = reason
        self.error = error
        with self._cv:
            self._done.set()
            self._cv.notify_all()
        self.router._stream_closed(self)

    def _consume(self, resp) -> bool:
        """Drain one SSE leg; True when the terminal event arrived,
        False when the connection broke mid-stream (failover time).
        Duplicate re-delivery (an index below what we hold) drops; a
        GAP (index above) is a protocol failure and raises."""
        try:
            for ev in _sse_events(resp):
                if ev.get("done"):
                    self._finish(ev.get("finish_reason"))
                    return True
                if "t" in ev:
                    i = int(ev["i"])
                    if i < len(self.tokens):
                        continue  # duplicate after an imprecise resume
                    if i > len(self.tokens):
                        raise RuntimeError(
                            f"token gap: expected index "
                            f"{len(self.tokens)}, got {i}")
                    self._deliver(int(ev["t"]))
        except (OSError, urllib.error.URLError) as e:
            # connection reset / refused / EOF mid-stream: the replica
            # is dying — report and let failover take over.  (A clean
            # EOF WITHOUT a done event lands here too, via the loop
            # simply ending.)
            self._last_io_error = e
            return False
        return self.finish_reason is not None

    def _run(self):
        try:
            replica = self.router._route(self)
            while True:
                if self._open_leg(replica):
                    return
                replica = self.router._await_failover(self)
                if replica is None:
                    self._finish(None, RuntimeError(
                        "replica died and no survivor could adopt "
                        "its journal"))
                    return
                self.failovers += 1
        except BaseException as e:
            self._finish(None, e)

    def _open_leg(self, replica: ReplicaHandle) -> bool:
        """One attach-and-drain leg on ``replica``; True = complete."""
        self.replica = replica.name
        try:
            if self._resume_from is not None:
                donor_rid, survivor = self._resume_from
                self._resume_from = None
                resp, meta = survivor.resume(donor_rid,
                                             trace=self.trace_id)
                self.remote_id = int(meta["request_id"])
                start = int(meta["start_index"])
                if start > len(self.tokens):
                    raise RuntimeError(
                        f"resume gap: consumer holds "
                        f"{len(self.tokens)} tokens, survivor "
                        f"resumes at {start}")
            else:
                resp, meta = replica.generate(
                    self.prompt_ids, self.max_new_tokens, self.kwargs,
                    trace=self.trace_id)
                self.remote_id = int(meta["request_id"])
            self.router._stream_attached(self, replica)
        except (OSError, urllib.error.URLError):
            return False  # replica died at attach: failover
        return self._consume(resp)

    _resume_from: Optional[tuple] = None
    _last_io_error: Optional[BaseException] = None


class FleetRouter:
    """Routes `submit()` traffic over the replica set and supervises
    it: a monitor thread polls every replica's ``/readyz`` on
    ``poll_interval_s`` cadence, refreshes the fleet ``/alertz``
    rollup, and triggers failover when a replica dies.

    ::

        router = FleetRouter(policy="affinity")
        router.add_replica("r0", "http://127.0.0.1:8100")
        router.add_replica("r1", "http://127.0.0.1:8101")
        router.start()
        stream = router.submit(prompt_ids, max_new_tokens=64)
        tokens = stream.result(timeout=120)
    """

    POLICIES = ("affinity", "round_robin")

    def __init__(self, policy: str = "affinity",
                 poll_interval_s: float = 0.05, dead_after: int = 3,
                 admit_timeout_s: float = 60.0,
                 rollup_every: int = 20, affinity_cap: int = 65536):
        if policy not in self.POLICIES:
            raise ValueError(f"policy must be one of {self.POLICIES}, "
                             f"got {policy!r}")
        self.policy = policy
        self.poll_interval_s = float(poll_interval_s)
        self.dead_after = int(dead_after)
        self.admit_timeout_s = float(admit_timeout_s)
        self.rollup_every = int(rollup_every)
        self.affinity_cap = int(affinity_cap)
        self._replicas: "OrderedDict[str, ReplicaHandle]" = \
            OrderedDict()
        self._affinity: "OrderedDict[str, str]" = OrderedDict()
        self._salt: Optional[bytes] = None
        self._page: Optional[int] = None
        self._config_fp: Optional[str] = None
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._inflight: Dict[str, set] = {}  # replica -> streams
        self._events: "deque" = deque(maxlen=64)
        self._rollup: dict = {}
        self._rr_next = 0
        self._monitor: Optional[threading.Thread] = None
        self._closing = threading.Event()
        self.stats = {"submitted": 0, "affinity_hits": 0,
                      "affinity_misses": 0, "failovers": 0,
                      "failover_seconds": None}
        from ..observability import opsserver

        opsserver.register_fleet(self)

    # -- membership ----------------------------------------------------------
    def add_replica(self, name: str, edge_url: str,
                    ops_url: Optional[str] = None,
                    journal_dir: Optional[str] = None) -> ReplicaHandle:
        """Join one replica.  Validates the wiring LOUDLY: a replica
        whose ops plane is off (``FLAGS_ops_port=0``, no listener)
        cannot be admitted against — refusing here beats a router that
        silently reads it never-ready forever."""
        rep = ReplicaHandle(name, edge_url, ops_url=ops_url,
                            journal_dir=journal_dir)
        info = rep.fetch_info()
        if rep.ops_url is None:
            ops_port = info.get("ops_port")
            if not ops_port:
                raise FleetConfigError(
                    f"replica {name!r} ({edge_url}) has no ops plane: "
                    f"/v1/info reports ops_port={ops_port!r}.  The "
                    f"fleet router admits by polling /readyz — start "
                    f"the replica with FLAGS_ops_port=<port> (or "
                    f"start_ops_server()) or pass ops_url= "
                    f"explicitly.  A replica the router cannot poll "
                    f"would silently never take traffic.")
            host = urllib.parse.urlsplit(rep.edge_url).hostname
            rep.ops_url = f"http://{host}:{int(ops_port)}"
        fp = info.get("config_fp")
        with self._lock:
            if self._config_fp is None:
                self._config_fp = fp
                self._salt = bytes.fromhex(info.get("route_salt") or "")
                self._page = int(info.get("page_size") or 0)
            elif fp != self._config_fp:
                raise FleetConfigError(
                    f"replica {name!r} config fingerprint {fp!r} "
                    f"differs from the fleet's {self._config_fp!r} — "
                    f"zero-loss failover requires identical model "
                    f"weights and engine construction config across "
                    f"replicas")
            self._replicas[name] = rep
            self._inflight.setdefault(name, set())
        rep.poll()
        return rep

    def start(self):
        """Start the monitor thread (idempotent)."""
        if self._monitor is None:
            self._monitor = threading.Thread(
                target=self._monitor_main, name="fleet-monitor",
                daemon=True)
            self._monitor.start()

    def close(self):
        self._closing.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
            self._monitor = None
        from ..observability import opsserver

        opsserver.deregister_fleet(self)

    # -- submission ----------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int = 32,
               **request_kwargs) -> FleetStream:
        """Route one request and stream its tokens (returns
        immediately; the `FleetStream`'s reader thread does the
        work).  With FLAGS_fleet_trace on, mints the request's fleet
        trace id here — the one identity that survives every HTTP hop
        and failover."""
        self.start()
        with self._lock:
            self.stats["submitted"] += 1
        trace_id = fleettrace.mint_trace_id() if fleettrace.enabled() \
            else None
        return FleetStream(self, prompt_ids, max_new_tokens,
                           request_kwargs, trace_id=trace_id)

    # -- routing -------------------------------------------------------------
    def _route_key(self, prompt_ids) -> List[str]:
        """Hex chain hashes of every full page of ``prompt_ids`` under
        the fleet's shared salt — byte-identical to the digests the
        replicas' prefix caches key on."""
        if not self._page or self._salt is None:
            return []
        h = self._salt
        out = []
        for i in range(len(prompt_ids) // self._page):
            h = _chain_hash(
                h, prompt_ids[i * self._page:(i + 1) * self._page])
            out.append(h.hex())
        return out

    def _route(self, stream: FleetStream) -> ReplicaHandle:
        """Pick the replica for one fresh request: affinity first
        (longest matching prefix hash held by an ADMISSIBLE replica),
        else predicted-cost-aware least-loaded / round-robin.  Blocks
        while no replica is admissible (bounded by
        ``admit_timeout_s``)."""
        hashes = self._route_key(stream.prompt_ids)
        t0_ns = _obs.now_ns() if fleettrace.enabled() else 0
        deadline = time.perf_counter() + self.admit_timeout_s
        with self._cond:
            while True:
                cands = [r for r in self._replicas.values()
                         if r.admissible()]
                if cands:
                    chosen, hit = self._pick(cands, hashes)
                    chosen.assigned_since_poll += 1
                    stream.affinity_hit = hit
                    self.stats["affinity_hits" if hit
                               else "affinity_misses"] += 1
                    for hx in hashes:
                        self._affinity[hx] = chosen.name
                        self._affinity.move_to_end(hx)
                    while len(self._affinity) > self.affinity_cap:
                        self._affinity.popitem(last=False)
                    (_obs.FLEET_AFFINITY_HITS if hit else
                     _obs.FLEET_AFFINITY_MISSES).inc(
                        replica=chosen.name)
                    if fleettrace.enabled():
                        # the routing decision as a span: which
                        # replica, why (affinity vs load), and how
                        # long admission blocked
                        args = {"replica": chosen.name,
                                "affinity_hit": bool(hit),
                                "prefix_hashes": len(hashes),
                                "headroom": int(chosen.headroom)}
                        if stream.trace_id is not None:
                            args["trace"] = stream.trace_id
                        _obs.record_span(
                            "router", "route", t0_ns,
                            _obs.now_ns() - t0_ns, args=args)
                    return chosen
                if time.perf_counter() >= deadline:
                    raise RuntimeError(
                        f"no admissible replica within "
                        f"{self.admit_timeout_s}s "
                        f"(replicas: "
                        f"{[(r.name, r.alive, r.ready, r.headroom) for r in self._replicas.values()]})")
                self._cond.wait(timeout=self.poll_interval_s)

    def _pick(self, cands: List[ReplicaHandle], hashes: List[str]):
        """(replica, affinity_hit) among admissible candidates."""
        if self.policy == "affinity" and hashes:
            by_name = {r.name: r for r in cands}
            for hx in reversed(hashes):  # longest prefix first
                name = self._affinity.get(hx)
                if name in by_name:
                    return by_name[name], True
        if self.policy == "round_robin":
            names = sorted(r.name for r in cands)
            name = names[self._rr_next % len(names)]
            self._rr_next += 1
            return next(r for r in cands if r.name == name), False
        # least-loaded with predicted-cost preference: replicas whose
        # calibrated predictor says the next step still meets the SLO
        # ceiling outrank ones it says will blow it
        cost_ok = [r for r in cands if r.slo_ok is not False]
        pool = cost_ok or cands
        return max(pool, key=lambda r:
                   r.headroom - r.assigned_since_poll), False

    # -- stream bookkeeping --------------------------------------------------
    def _stream_attached(self, stream: FleetStream,
                         replica: ReplicaHandle):
        with self._lock:
            self._inflight.setdefault(replica.name, set()).add(stream)

    def _stream_closed(self, stream: FleetStream):
        with self._lock:
            for streams in self._inflight.values():
                streams.discard(stream)

    # -- failover ------------------------------------------------------------
    def _await_failover(self,
                        stream: FleetStream) -> Optional[ReplicaHandle]:
        """A stream's SSE leg broke: make sure its replica's failover
        runs (first caller executes it; the monitor may beat us to
        it), then hand back the survivor + resume coordinates."""
        with self._lock:
            rep = self._replicas.get(stream.replica)
        if rep is None:
            return None
        self._failover(rep)
        if not rep.fo_event.wait(timeout=120):
            return None
        migration = rep.migration
        if migration is None:
            return None
        survivor_name, migrated = migration
        with self._lock:
            survivor = self._replicas.get(survivor_name)
        if survivor is None:
            return None
        if stream.remote_id is None:
            # the replica died between admission and the meta event:
            # we cannot name our journaled twin, so re-submit fresh
            # (zero tokens were delivered; the orphaned adoptee on
            # the survivor runs out harmlessly)
            return self._route(stream)
        entry = migrated.get(stream.remote_id) or \
            migrated.get(str(stream.remote_id))
        if entry is None:
            # admitted on the dead replica but never journaled (died
            # pre-fsync with journal_fsync != always): re-submit
            return self._route(stream)
        stream._resume_from = (stream.remote_id, survivor)
        return survivor

    def _failover(self, dead: ReplicaHandle):
        """Adopt ``dead``'s journal into a survivor exactly once."""
        with dead._fo_lock:
            if dead.failed_over:
                return
            dead.failed_over = True
        t0 = time.perf_counter()
        t0_ns = _obs.now_ns() if fleettrace.enabled() else 0
        with self._lock:
            dead.alive = False
            dead.ready = False
            inflight = list(self._inflight.get(dead.name, ()))
        delivered = {s.remote_id: len(s.tokens) for s in inflight
                     if s.remote_id is not None}
        traces = {s.remote_id: s.trace_id for s in inflight
                  if s.remote_id is not None and
                  s.trace_id is not None}
        self._events.append({
            "event": "replica_dead", "replica": dead.name,
            "inflight": len(inflight)})
        if fleettrace.enabled():
            _obs.record_span("router", "replica_dead", t0_ns, 0,
                             args={"replica": dead.name,
                                   "inflight": len(inflight)})
        survivor = None
        deadline = time.perf_counter() + self.admit_timeout_s
        migrated: dict = {}
        while time.perf_counter() < deadline:
            with self._lock:
                cands = [r for r in self._replicas.values()
                         if r is not dead and r.alive and r.ready]
            if cands:
                survivor = max(cands, key=lambda r: r.headroom)
                try:
                    t_adopt = _obs.now_ns() if fleettrace.enabled() \
                        else 0
                    migrated = survivor.adopt(dead.journal_dir,
                                              delivered,
                                              traces=traces or None)
                    if fleettrace.enabled():
                        _obs.record_span(
                            "router", "adopt", t_adopt,
                            _obs.now_ns() - t_adopt,
                            args={"donor": dead.name,
                                  "survivor": survivor.name,
                                  "migrated": len(migrated)})
                    break
                except Exception as e:
                    self._events.append({
                        "event": "adopt_failed",
                        "replica": survivor.name, "error": str(e)})
                    survivor = None
            time.sleep(self.poll_interval_s)
        if survivor is None:
            dead.migration = None
            dead.fo_event.set()
            self._events.append({"event": "failover_failed",
                                 "replica": dead.name})
            return
        migrated = {int(k): v for k, v in migrated.items()}
        dt = time.perf_counter() - t0
        with self._lock:
            # future requests for the dead replica's prefixes now
            # belong to the survivor holding the adopted pages
            for hx, name in list(self._affinity.items()):
                if name == dead.name:
                    self._affinity[hx] = survivor.name
            self.stats["failovers"] += 1
            self.stats["failover_seconds"] = dt
        _obs.FLEET_FAILOVERS.inc()
        _obs.FLEET_FAILOVER_SECONDS.set(dt)
        if fleettrace.enabled():
            _obs.record_span(
                "router", "failover", t0_ns, _obs.now_ns() - t0_ns,
                args={"replica": dead.name, "survivor": survivor.name,
                      "migrated": len(migrated)})
        self._events.append({
            "event": "failover", "replica": dead.name,
            "survivor": survivor.name, "migrated": len(migrated),
            "delivered_reported": sum(delivered.values()),
            "seconds": round(dt, 4)})
        dead.migration = (survivor.name, migrated)
        dead.fo_event.set()

    # -- monitor -------------------------------------------------------------
    def _monitor_main(self):
        rounds = 0
        while not self._closing.is_set():
            with self._lock:
                reps = list(self._replicas.values())
            ready = 0
            for rep in reps:
                if not rep.alive:
                    continue
                if rep.poll():
                    ready += 1
                elif rep.failures >= self.dead_after and \
                        not rep.failed_over:
                    # consecutive refusals = dead process (kill -9
                    # closes the listener instantly); streams may not
                    # have noticed yet — the monitor runs failover so
                    # even a replica with NO open streams gets its
                    # queued journal replayed
                    self._failover(rep)
            _obs.FLEET_REPLICAS_READY.set(ready)
            with self._cond:
                self._cond.notify_all()
            rounds += 1
            if rounds % self.rollup_every == 1:
                self._refresh_rollup(ready)
            self._closing.wait(self.poll_interval_s)

    def _refresh_rollup(self, ready: int):
        from ..observability.alerts import fleet_rollup

        with self._lock:
            reps = list(self._replicas.items())
            events = list(self._events)
        docs = {}
        for name, rep in reps:
            doc = rep.alertz() if rep.alive else None
            if doc is not None:
                # a replica's /alertz may itself embed a fleet section
                # (this router registers with the shared ops plane in
                # in-process fleets) — strip it or rollups would nest
                doc = {k: v for k, v in doc.items() if k != "fleet"}
            docs[name] = doc
        rollup = fleet_rollup(docs, events=events,
                              replicas_ready=ready)
        with self._lock:
            self._rollup = rollup

    def alertz_rollup(self) -> dict:
        """The cached fleet-level `/alertz` section (see
        `observability.alerts.fleet_rollup`); refreshed by the monitor
        every ``rollup_every`` polls.  Cache-only by design: this is
        called from the ops server's own /alertz handler, and
        refreshing synchronously would recurse through HTTP (handler
        -> rollup -> GET replica /alertz -> handler ...)."""
        with self._lock:
            if self._rollup:
                return dict(self._rollup)
            return {"replicas": {}, "reachable": 0, "firing": {},
                    "paging": False, "pending": True}

    # -- fleet rollup (/fleetz) ----------------------------------------------
    @staticmethod
    def _fetch_text(url: str, timeout: float) -> Optional[str]:
        try:
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                return resp.read().decode("utf-8", "replace")
        except Exception:
            return None

    def fleetz(self, trace: Optional[str] = None) -> dict:
        """The fleet-wide rollup surface (served at ``/fleetz``): one
        document aggregating every replica's ``/metrics`` (Prometheus
        text), ``/alertz`` and ``/statusz``, the poll-measured RTT and
        NTP-style clock-offset estimates, the router's failover event
        log, and — the point of the exercise — ONE merged chrome trace
        (`observability.fleettrace.merge_fleet_trace`): each replica's
        span buffer is pulled over its edge's ``/tracez/spans``,
        shifted onto the router's clock, and folded together with the
        router's own routing/failover spans, so a killed-and-adopted
        request renders as a single contiguous lane under its trace
        id.  ``trace`` narrows the span pull to one trace id.

        Synchronous by design (unlike `alertz_rollup`): ``/fleetz`` is
        served by the ROUTER process's ops plane but fetches REPLICA
        endpoints, so there is no handler recursion to avoid."""
        with self._lock:
            reps = list(self._replicas.items())
            events = list(self._events)
            stats = dict(self.stats)
        replicas: Dict[str, dict] = {}
        spans: Dict[str, list] = {}
        offsets: Dict[str, int] = {}
        q = "?trace=" + urllib.parse.quote(trace) if trace else ""
        for name, rep in reps:
            entry = {
                "alive": rep.alive, "ready": rep.ready,
                "headroom": int(rep.headroom),
                "poll_rtt_s": rep.poll_rtt_s,
                "clock_offset_ns": rep.clock_offset_ns(),
            }
            if rep.alive and rep.ops_url:
                entry["metrics"] = self._fetch_text(
                    rep.ops_url + "/metrics", rep.timeout_s)
                try:
                    entry["alertz"] = _get_json(
                        rep.ops_url + "/alertz", rep.timeout_s)
                except Exception:
                    entry["alertz"] = None
                try:
                    entry["statusz"] = _get_json(
                        rep.ops_url + "/statusz", rep.timeout_s)
                except Exception:
                    entry["statusz"] = None
            if rep.alive:
                try:
                    doc = _get_json(
                        rep.edge_url + "/tracez/spans" + q,
                        rep.timeout_s)
                    spans[name] = doc.get("spans") or []
                    offsets[name] = rep.clock_offset_ns()
                except Exception:
                    pass
            replicas[name] = entry
        # the router's own span buffer joins the merge at offset 0 —
        # routing and failover spans share the fleet timeline
        from ..observability import tracing

        local = fleettrace.span_slice(tracing.spans(), trace=trace)
        if local:
            spans["router"] = local
            offsets["router"] = 0
        return {
            "replicas": replicas,
            "events": events,
            "stats": stats,
            "alerts": self.alertz_rollup(),
            "trace": fleettrace.merge_fleet_trace(spans, offsets),
        }
