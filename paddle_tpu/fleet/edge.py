"""The HTTP/SSE serving edge on one engine replica.

`EdgeServer` is the network half of `inference.frontend.ServingFrontend`:
the frontend's asyncio driver runs on a dedicated daemon thread, and a
stdlib `ThreadingHTTPServer` (the `observability.opsserver` daemon
pattern — read that module first) bridges handler threads into it with
``asyncio.run_coroutine_threadsafe``.  Endpoints:

=================== ======================================================
endpoint            serves
=================== ======================================================
POST /v1/generate   body ``{"prompt_ids": [...], "max_new_tokens": N,
                    ...}`` (eos_token_id / priority / deadline_ms /
                    slo_ttft_ms / slo_tpot_ms pass through to
                    `DecodeEngine.add_request`).  Streams Server-Sent
                    Events: one ``meta`` event (``request_id``,
                    ``start_index``), one event per token
                    (``{"i": index, "t": token}``), one terminal event
                    (``{"done": true, "finish_reason": ..., "n": total}``)
POST /v1/adopt      body ``{"journal_dir": ..., "delivered": {id: n}}`` —
                    fleet failover: replay a dead sibling replica's
                    journal into THIS replica
                    (`ServingFrontend.adopt`) and park a relay per
                    migrated request for ``/v1/resume`` to drain.
                    Returns the migration map (donor id -> fresh id,
                    start_index, done)
GET /v1/resume      ``?request=<donor id>`` — one-shot: stream the
                    adopted request's remaining tokens as SSE with the
                    same framing ``/v1/generate`` uses, starting at
                    ``start_index`` (snapshot-known undelivered tokens
                    backfill first, live recompute follows) — the
                    reconnecting consumer sees token-for-token continuity
GET /v1/info        replica identity: engine id, config fingerprint,
                    routing salt + page size (the router's hash inputs),
                    ops-plane port, journal directory
GET /tracez/spans   this replica's span-buffer slice as JSON
                    (``?trace=<id>`` and/or ``?since_ns=&until_ns=``),
                    plus the replica clock (``now_ns``) — the fleet
                    rollup's merge input (observability.fleettrace)
=================== ======================================================

With ``FLAGS_fleet_trace`` on, ``/v1/generate`` / ``/v1/adopt`` /
``/v1/resume`` read the ``x-paddle-trace`` header (the router mints the
id) and thread it through the frontend onto the engine request, so
engine-side request spans and flight records tag themselves with the
fleet-wide trace id; SSE delivery, adoption and resume each record
spans on an ``edge`` track.  Flag off (default): the header is never
read, no edge spans record — bit-exact with the pre-trace edge.

A disconnected ``/v1/generate`` consumer cancels its request (queued or
running); a disconnected ``/v1/resume`` consumer does NOT — the adopted
request keeps generating so a second resume attempt (or a second
failover) still loses nothing.
"""
from __future__ import annotations

import asyncio
import contextlib
import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional
from urllib.parse import parse_qs, urlparse

from ..observability import fleettrace

__all__ = ["EdgeServer"]

# generation kwargs the edge forwards verbatim to add_request
_REQUEST_KWARGS = ("eos_token_id", "priority", "deadline_ms",
                   "slo_ttft_ms", "slo_tpot_ms")


def _edge_span(name: str, tid: int = 0, **args):
    """RAII span on the ``edge`` track — a no-op context unless
    FLAGS_fleet_trace, so the default edge records nothing."""
    if not fleettrace.enabled():
        return contextlib.nullcontext()
    from ..observability import tracing

    kept = {k: v for k, v in args.items() if v is not None}
    return tracing.span("edge", name, tid=int(tid), args=kept or None)


class _Relay:
    """Thread-safe conveyor from the frontend's event loop to one SSE
    handler thread: the async pump feeds ``("tok", value)`` /
    ``("done", reason, total)`` items, the handler drains and frames
    them.  ``start_index`` is the absolute index of the first token
    the consumer will see (nonzero on resumed streams)."""

    def __init__(self, start_index: int = 0):
        self.q: "queue.Queue" = queue.Queue()
        self.start_index = int(start_index)
        self.request_id: Optional[int] = None
        self.stream = None  # TokenStream, for cancel-on-disconnect
        self.trace: Optional[str] = None  # fleet trace id, if any


class EdgeServer:
    """One replica's serving edge: engine + frontend + HTTP listener.

    ::

        edge = EdgeServer(engine, port=0)   # 0 = ephemeral (tests)
        port = edge.start()
        ...
        edge.close()

    ``frontend_kwargs`` pass to `ServingFrontend` (queue depth, stream
    buffer).  The frontend's driver loop runs on a daemon thread owned
    by this object; handler threads never touch the engine directly —
    every mutation goes through the frontend's control queue, exactly
    like an in-process caller's would."""

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0,
                 submit_timeout_s: float = 120.0,
                 stream_idle_timeout_s: float = 600.0,
                 **frontend_kwargs):
        self.engine = engine
        self.host = str(host)
        self.port = int(port)
        self.submit_timeout_s = float(submit_timeout_s)
        self.stream_idle_timeout_s = float(stream_idle_timeout_s)
        self._fe_kwargs = dict(frontend_kwargs)
        self.frontend = None
        self._aloop: Optional[asyncio.AbstractEventLoop] = None
        self._loop_thread: Optional[threading.Thread] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self._adopted: Dict[int, _Relay] = {}  # donor id -> relay
        self._adopt_lock = threading.Lock()
        self._closed = False

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> int:
        """Start the frontend loop thread + HTTP listener; returns the
        bound port.  Idempotent."""
        if self._httpd is not None:
            return self._httpd.server_address[1]
        if self._closed:
            raise RuntimeError("edge is closed")
        ready = threading.Event()
        boot_err: list = []

        def _loop_main():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._aloop = loop

            async def _boot():
                from ..inference.frontend import ServingFrontend

                self.frontend = ServingFrontend(self.engine,
                                                **self._fe_kwargs)
                await self.frontend.start()
            try:
                loop.run_until_complete(_boot())
            except Exception as e:  # surface on start(), not the log
                boot_err.append(e)
                ready.set()
                return
            ready.set()
            loop.run_forever()
            # close() stopped the loop; drain callbacks then close
            loop.run_until_complete(loop.shutdown_asyncgens())
            loop.close()

        self._loop_thread = threading.Thread(
            target=_loop_main, name="paddle-fleet-edge-loop",
            daemon=True)
        self._loop_thread.start()
        ready.wait()
        if boot_err:
            raise boot_err[0]
        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          _EdgeHandler)
        self._httpd.daemon_threads = True
        self._httpd.edge = self  # handler back-pointer
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="paddle-fleet-edge-http", daemon=True)
        self._http_thread.start()
        return self._httpd.server_address[1]

    @property
    def bound_port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    def close(self, drain: bool = False):
        """Stop the listener and the frontend (``drain=True`` serves
        outstanding requests to completion first)."""
        if self._closed:
            return
        self._closed = True
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            if self._http_thread is not None:
                self._http_thread.join(timeout=5)
        if self._aloop is not None and self.frontend is not None:
            try:
                asyncio.run_coroutine_threadsafe(
                    self.frontend.close(drain=drain),
                    self._aloop).result(timeout=60)
            except Exception:
                pass
            self._aloop.call_soon_threadsafe(self._aloop.stop)
            if self._loop_thread is not None:
                self._loop_thread.join(timeout=10)

    # -- handler-thread entries ----------------------------------------------
    def _run(self, coro, timeout: float):
        return asyncio.run_coroutine_threadsafe(
            coro, self._aloop).result(timeout=timeout)

    async def _pump(self, stream, relay: _Relay):
        """Event-loop side of one SSE stream: token queue -> relay."""
        try:
            async for tok in stream:
                relay.q.put(("tok", int(tok)))
        finally:
            relay.q.put(("done", stream.finish_reason,
                         len(stream.request.generated_ids)))

    def open_stream(self, prompt_ids, max_new_tokens: int,
                    kwargs: dict) -> _Relay:
        """Submit one request; returns its relay (meta already
        resolved).  Raises whatever `add_request` would."""
        relay = _Relay()
        relay.trace = kwargs.get("trace_id")

        async def _submit():
            stream = await self.frontend.submit(
                list(prompt_ids), int(max_new_tokens), **kwargs)
            relay.request_id = int(stream.request.request_id)
            relay.stream = stream
            # pump as a loop task: tokens flow while the handler
            # thread is blocked writing to a slow consumer
            asyncio.ensure_future(self._pump(stream, relay))
            return stream
        self._run(_submit(), self.submit_timeout_s)
        return relay

    def cancel_stream(self, relay: _Relay):
        """Consumer went away mid-generate: stop the request."""
        if relay.stream is None or self._aloop is None:
            return
        try:
            asyncio.run_coroutine_threadsafe(relay.stream.cancel(),
                                             self._aloop)
        except RuntimeError:
            pass

    def adopt(self, journal_dir: str,
              delivered: Optional[Dict[int, int]] = None,
              traces: Optional[Dict[int, str]] = None) -> dict:
        """Failover entry (``POST /v1/adopt``): replay the dead
        sibling's journal into this replica's engine and park one
        relay per migrated request for ``/v1/resume``.  Returns the
        JSON-safe migration map keyed by donor request id.  ``traces``
        (router-supplied donor id -> fleet trace id) is the fallback
        for trace-less journals — the journal's own record wins."""
        delivered = {int(k): int(v)
                     for k, v in (delivered or {}).items()}
        traces = {int(k): str(v) for k, v in (traces or {}).items()}

        async def _adopt():
            return await self.frontend.adopt(journal_dir,
                                             delivered=delivered,
                                             traces=traces or None)
        with _edge_span("adopt", donor=journal_dir):
            out = self._run(_adopt(), self.submit_timeout_s)
        migrated = {}
        with self._adopt_lock:
            for rid, info in out.items():
                relay = _Relay(start_index=info["start_index"])
                relay.request_id = int(info["request_id"])
                relay.trace = info.get("trace")
                # backfill BEFORE the pump is scheduled: the relay
                # queue then orders snapshot-known tokens ahead of
                # live recompute by construction
                for t in info["backfill"]:
                    relay.q.put(("tok", int(t)))
                asyncio.run_coroutine_threadsafe(
                    self._pump(info["stream"], relay), self._aloop)
                self._adopted[int(rid)] = relay
                migrated[int(rid)] = {
                    "request_id": int(info["request_id"]),
                    "start_index": int(info["start_index"]),
                    "backfill_tokens": len(info["backfill"]),
                    "done": bool(info["done"]),
                }
                if relay.trace is not None:
                    migrated[int(rid)]["trace"] = relay.trace
        return migrated

    def pop_adopted(self, donor_id: int) -> Optional[_Relay]:
        """One-shot claim of a migrated request's relay (the resume
        stream is exactly-once: a second resume gets 404, it does not
        restart the token sequence)."""
        with self._adopt_lock:
            return self._adopted.pop(int(donor_id), None)

    def info(self) -> dict:
        from ..observability import opsserver

        eng = self.engine
        return {
            "engine_id": int(eng._engine_id),
            "config_fp": eng.config_fingerprint().hex(),
            "route_salt": eng._model_salt.hex(),
            "page_size": int(eng._page),
            "prefix_cache": bool(eng._prefix_cache),
            "cache_generated_pages": bool(eng._cache_generated),
            "max_batch_size": int(eng._slots),
            "ops_port": opsserver.ops_server_port(),
            "journal": eng.journal_info(),
        }


class _EdgeHandler(BaseHTTPRequestHandler):
    server_version = "paddle-fleet-edge/1"

    def log_message(self, *args):  # noqa: D102 - silence request logs
        pass

    @property
    def edge(self) -> EdgeServer:
        return self.server.edge

    # -- plumbing ------------------------------------------------------------
    def _send_json(self, obj, code: int = 200):
        data = json.dumps(obj, default=str).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _body(self) -> dict:
        n = int(self.headers.get("Content-Length") or 0)
        if n <= 0:
            return {}
        return json.loads(self.rfile.read(n) or b"{}")

    def _sse_begin(self):
        # HTTP/1.0 + no Content-Length: the consumer reads events
        # until the connection closes (exactly what SSE wants here)
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.end_headers()

    def _sse_event(self, obj):
        self.wfile.write(b"data: " +
                         json.dumps(obj, separators=(",", ":")).encode()
                         + b"\n\n")
        self.wfile.flush()

    def _sse_drain(self, relay: _Relay):
        """Meta event, then token events, then the terminal event."""
        self._sse_begin()
        self._sse_event({"request_id": relay.request_id,
                         "start_index": relay.start_index})
        idx = relay.start_index
        while True:
            item = relay.q.get(
                timeout=self.edge.stream_idle_timeout_s)
            if item[0] == "tok":
                self._sse_event({"i": idx, "t": item[1]})
                idx += 1
            else:
                self._sse_event({"done": True, "finish_reason": item[1],
                                 "n": item[2]})
                return

    # -- routes --------------------------------------------------------------
    def do_GET(self):  # noqa: N802 - http.server API
        try:
            url = urlparse(self.path)
            if url.path == "/v1/info":
                self._send_json(self.edge.info())
            elif url.path == "/v1/resume":
                self._resume(parse_qs(url.query))
            elif url.path == "/tracez/spans":
                self._tracez_spans(parse_qs(url.query))
            else:
                self._send_json({"error": f"unknown endpoint "
                                          f"{url.path!r}"}, code=404)
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:
            self._try_error(e)

    def do_POST(self):  # noqa: N802 - http.server API
        try:
            url = urlparse(self.path)
            if url.path == "/v1/generate":
                self._generate()
            elif url.path == "/v1/adopt":
                body = self._body()
                if not body.get("journal_dir"):
                    self._send_json({"error": "journal_dir required"},
                                    code=400)
                    return
                self._send_json({"migrated": self.edge.adopt(
                    str(body["journal_dir"]),
                    body.get("delivered") or {},
                    traces=self._traces_in(body))})
            else:
                self._send_json({"error": f"unknown endpoint "
                                          f"{url.path!r}"}, code=404)
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:
            self._try_error(e)

    def _try_error(self, e: Exception):
        try:
            self._send_json({"error": f"{type(e).__name__}: {e}"},
                            code=500)
        except Exception:
            pass

    def _trace_in(self) -> Optional[str]:
        """The request's fleet trace id — only read while
        FLAGS_fleet_trace is on (default off never inspects headers)."""
        if not fleettrace.enabled():
            return None
        trace = self.headers.get(fleettrace.TRACE_HEADER)
        return str(trace) if trace else None

    def _traces_in(self, body: dict) -> Optional[dict]:
        if not fleettrace.enabled():
            return None
        return body.get("traces") or None

    def _generate(self):
        body = self._body()
        prompt = body.get("prompt_ids")
        if not prompt:
            self._send_json({"error": "prompt_ids required"}, code=400)
            return
        kwargs = {k: body[k] for k in _REQUEST_KWARGS if k in body}
        trace = self._trace_in()
        if trace is not None:
            kwargs["trace_id"] = trace
        try:
            relay = self.edge.open_stream(
                prompt, body.get("max_new_tokens", 32), kwargs)
        except Exception as e:
            # admission validation (empty prompt, over-horizon, pool
            # too small) surfaces as a 4xx, not a broken stream
            self._send_json({"error": f"{type(e).__name__}: {e}"},
                            code=400)
            return
        try:
            with _edge_span("sse", tid=relay.request_id or 0,
                            request=relay.request_id, trace=relay.trace):
                self._sse_drain(relay)
        except (BrokenPipeError, ConnectionResetError):
            self.edge.cancel_stream(relay)  # consumer went away

    def _resume(self, query):
        rid = query.get("request", [None])[0]
        if rid is None:
            self._send_json({"error": "request id required"}, code=400)
            return
        relay = self.edge.pop_adopted(int(rid))
        if relay is None:
            self._send_json(
                {"error": f"no adopted stream for request {rid} "
                          f"(already resumed, or never migrated "
                          f"here)"}, code=404)
            return
        # a dropped resume consumer does NOT cancel the request: the
        # engine keeps generating and a re-adoption (second failover)
        # still covers every token
        with _edge_span("resume", tid=relay.request_id or 0,
                        request=relay.request_id, trace=relay.trace):
            self._sse_drain(relay)

    def _tracez_spans(self, query):
        """``GET /tracez/spans`` — this replica's span-buffer slice
        (read-only; served regardless of FLAGS_fleet_trace so a
        router-side merge can still collect engine spans)."""
        from ..observability import tracing

        trace = query.get("trace", [None])[0]
        since = query.get("since_ns", [None])[0]
        until = query.get("until_ns", [None])[0]
        out = fleettrace.span_slice(
            tracing.spans(), trace=trace,
            since_ns=None if since is None else int(since),
            until_ns=None if until is None else int(until))
        self._send_json({
            "engine_id": int(self.edge.engine._engine_id),
            "now_ns": int(tracing.now_ns()),
            "spans": out,
        })
