"""Custom C++ operator extension.

Reference: `paddle/fluid/extension/` (`PD_BUILD_OP` macro
`ext_op_meta_info.h:502`, `paddle::Tensor` `ext_tensor.h`) + build helpers
`python/paddle/utils/cpp_extension/` (JIT `load(...)` and
`CppExtension`/`setup` flows) + runtime loader
`framework/custom_operator.cc`.

TPU-native re-design: XLA owns device codegen, so a custom op is a **host
callback**: the user writes plain C functions with a flat C ABI, `load()`
compiles them with g++ into a shared library, and each op is exposed as a
function that routes through `jax.pure_callback` — eager AND jit-traced
code both work, and XLA schedules the host transfer. An optional
`<name>_grad` C function wires a custom VJP, mirroring the reference's
grad-op registration.

C ABI convention (float32, same-shape unary ops — the common custom-op
case; richer signatures can marshal through multiple calls):

    extern "C" void my_op(const float* x, float* out, int64_t n);
    extern "C" void my_op_grad(const float* x, const float* grad_out,
                               float* grad_x, int64_t n);   // optional
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["load", "CppExtension", "CustomOpModule", "get_build_directory"]


def get_build_directory() -> str:
    d = os.environ.get("PADDLE_EXTENSION_DIR",
                       os.path.join(tempfile.gettempdir(),
                                    "paddle_tpu_extensions"))
    os.makedirs(d, exist_ok=True)
    return d


def _compile(name: str, sources: Sequence[str],
             extra_cxx_flags: Sequence[str] = ()) -> str:
    """g++ the sources into <build_dir>/<name>-<hash>.so (cached)."""
    srcs = [os.path.abspath(s) for s in sources]
    h = hashlib.sha1()
    for s in srcs:
        with open(s, "rb") as f:
            h.update(f.read())
    h.update(" ".join(extra_cxx_flags).encode())
    out = os.path.join(get_build_directory(),
                       f"{name}-{h.hexdigest()[:12]}.so")
    if not os.path.exists(out):
        # compile to a unique temp path and rename: concurrent workers
        # (fleet launch) racing on the same cache entry must never dlopen
        # a half-written library
        tmp = f"{out}.{os.getpid()}.tmp"
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
               *extra_cxx_flags, *srcs, "-o", tmp]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"custom op build failed:\n{proc.stderr[-4000:]}")
        os.replace(tmp, out)
    return out


class CustomOp:
    """A single compiled op bound as an eager+jit-compatible function."""

    def __init__(self, lib, name: str, has_grad: bool):
        self._name = name
        self._fwd = getattr(lib, name)
        self._fwd.restype = None
        self._fwd.argtypes = [ctypes.POINTER(ctypes.c_float),
                              ctypes.POINTER(ctypes.c_float),
                              ctypes.c_int64]
        self._bwd = None
        if has_grad:
            self._bwd = getattr(lib, name + "_grad")
            self._bwd.restype = None
            self._bwd.argtypes = [ctypes.POINTER(ctypes.c_float)] * 3 + \
                [ctypes.c_int64]

    # -- host kernels -------------------------------------------------------
    def _run_fwd(self, x: np.ndarray) -> np.ndarray:
        x = np.ascontiguousarray(x, np.float32)
        out = np.empty_like(x)
        self._fwd(x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                  out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                  x.size)
        return out

    def _run_bwd(self, x: np.ndarray, gy: np.ndarray) -> np.ndarray:
        x = np.ascontiguousarray(x, np.float32)
        gy = np.ascontiguousarray(gy, np.float32)
        gx = np.empty_like(x)
        self._bwd(x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                  gy.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                  gx.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                  x.size)
        return gx

    # -- jax-facing op ------------------------------------------------------
    def build(self):
        import jax
        import jax.numpy as jnp

        from ..core.dispatch import dispatch

        def fwd_cb(a):
            return jax.pure_callback(
                self._run_fwd, jax.ShapeDtypeStruct(a.shape, jnp.float32),
                a, vmap_method="sequential")

        if self._bwd is None:
            def f(a):
                return fwd_cb(a)
        else:
            @jax.custom_vjp
            def f(a):
                return fwd_cb(a)

            def f_fwd(a):
                return fwd_cb(a), a

            def f_bwd(a, gy):
                gx = jax.pure_callback(
                    self._run_bwd,
                    jax.ShapeDtypeStruct(a.shape, jnp.float32), a, gy,
                    vmap_method="sequential")
                return (gx,)

            f.defvjp(f_fwd, f_bwd)

        def op(x):
            return dispatch(f, x)

        op.__name__ = self._name
        return op


class CustomOpModule:
    """Result of `load()`: compiled library + bound op functions, mirroring
    the module object the reference's JIT `load` returns."""

    def __init__(self, so_path: str, op_names: Sequence[str]):
        self._so_path = so_path
        self._lib = ctypes.CDLL(so_path)
        self._ops: Dict[str, object] = {}
        for name in op_names:
            has_grad = hasattr(self._lib, name + "_grad")
            self._ops[name] = CustomOp(self._lib, name, has_grad).build()
            setattr(self, name, self._ops[name])

    def get_op(self, name):
        return self._ops[name]

    def op_names(self):
        return list(self._ops)


def _discover_ops(sources: Sequence[str]) -> List[str]:
    """Parse `extern "C" void <name>(` exports from the sources (the
    reference discovers ops from PD_BUILD_OP registrations similarly)."""
    import re

    names = []
    pat = re.compile(r'extern\s+"C"\s+void\s+([A-Za-z_][A-Za-z0-9_]*)\s*\(')
    for s in sources:
        with open(s) as f:
            for m in pat.finditer(f.read()):
                n = m.group(1)
                if not n.endswith("_grad"):
                    names.append(n)
    return names


def load(name: str, sources: Sequence[str], extra_cxx_flags=(),
         op_names: Optional[Sequence[str]] = None,
         verbose=False) -> CustomOpModule:
    """JIT-compile custom ops (reference
    `python/paddle/utils/cpp_extension/extension_utils.py load`)."""
    so = _compile(name, sources, extra_cxx_flags)
    ops = list(op_names) if op_names else _discover_ops(sources)
    if not ops:
        raise ValueError("no extern \"C\" op functions found in sources")
    return CustomOpModule(so, ops)


class CppExtension:
    """setup()-flow description object (reference `CppExtension`); with the
    JIT path above being primary on TPU, this is a thin record consumed by
    setuptools-based builds."""

    def __init__(self, sources, name=None, extra_compile_args=()):
        self.sources = list(sources)
        self.name = name
        self.extra_compile_args = list(extra_compile_args)
