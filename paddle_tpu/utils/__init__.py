from . import clip
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue


def stable_rng(name: str, mode: str):
    """Deterministic numpy RandomState keyed by (name, mode) via crc32 —
    NOT hash(), whose per-process randomization would give distributed
    workers different synthetic corpora."""
    import zlib

    import numpy as np

    return np.random.RandomState(
        zlib.crc32(f"{name}:{mode}".encode()) % (2 ** 31))


def try_import(name):
    import importlib

    try:
        return importlib.import_module(name)
    except ImportError:
        return None
