from . import clip
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue


def try_import(name):
    import importlib

    try:
        return importlib.import_module(name)
    except ImportError:
        return None
