"""Gradient clipping.

Reference: `python/paddle/fluid/clip.py:152,243,345` — ClipGradByValue,
ClipGradByNorm, ClipGradByGlobalNorm (the hybrid-parallel-aware global-norm
variant lives in fleet's sharding helper; here the same class works under
pjit because the norm reduction is traced into the sharded step).
"""
from __future__ import annotations

import jax.numpy as jnp


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError

    # functional form used by jit'd optimizer cores: grads is a list of arrays
    def clip_arrays(self, grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def clip_arrays(self, grads):
        return [jnp.clip(g, self.min, self.max) if g is not None else None
                for g in grads]

    def __call__(self, params_grads):
        from ..core.tensor import Tensor

        return [
            (p, Tensor(jnp.clip(g._array, self.min, self.max)) if g is not None else None)
            for p, g in params_grads
        ]


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def clip_arrays(self, grads):
        out = []
        for g in grads:
            if g is None:
                out.append(None)
                continue
            n = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(n, 1e-12), 1.0)
            out.append((g.astype(jnp.float32) * scale).astype(g.dtype))
        return out

    def __call__(self, params_grads):
        from ..core.tensor import Tensor

        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, None))
                continue
            (ga,) = self.clip_arrays([g._array])
            out.append((p, Tensor(ga)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def clip_arrays(self, grads):
        sq = sum(
            jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads if g is not None
        )
        global_norm = jnp.sqrt(sq)
        scale = jnp.minimum(self.clip_norm / jnp.maximum(global_norm, 1e-12), 1.0)
        return [
            None if g is None else (g.astype(jnp.float32) * scale).astype(g.dtype)
            for g in grads
        ]

    def __call__(self, params_grads):
        from ..core.tensor import Tensor

        grads = [None if g is None else g._array for _, g in params_grads]
        clipped = self.clip_arrays(grads)
        return [
            (p, None if c is None else Tensor(c))
            for (p, _), c in zip(params_grads, clipped)
        ]


GradientClipByValue = ClipGradByValue
GradientClipByNorm = ClipGradByNorm
GradientClipByGlobalNorm = ClipGradByGlobalNorm
