"""SPMD pipeline-parallel schedule over the 'pp' mesh axis.

Reference semantics: `SectionWorker::TrainFiles` micro-batch schedules
(`paddle/fluid/framework/section_worker.cc:99` F-then-B, `:144` 1F1B) with
NCCL p2p `send_v2`/`recv_v2` between stages.

TPU-native (SURVEY.md §7 row "send_v2/recv_v2 PP"): homogeneous stage
parameters are STACKED on a leading axis sharded over 'pp', and the whole
microbatch schedule runs inside one jit as a per-device program:
each schedule tick applies the local stage and rotates activations to the
next stage with `lax.ppermute` (collective-permute on ICI).  Reverse-mode AD
through the schedule (ppermute transposes to the reverse ring) yields the
backward pipeline automatically, so fwd+bwd+update is ONE XLA program —
no per-microbatch host scheduling like the reference's SectionWorker loop.
"""
from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_local(stage_fn: Callable, stage_params, microbatches,
                   axis_name: str = "pp"):
    """Per-device schedule body (call inside shard_map).

    stage_fn(params, x) -> y with y.shape == x.shape (homogeneous stages).
    stage_params: this device's stage parameters (leading 'pp' dim removed).
    microbatches: [M, mb, ...] — replicated input; stage 0 ingests them.
    Returns [M, mb, ...] outputs, replicated (psum).
    """
    L = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    M = microbatches.shape[0]
    T = M + L - 1
    perm = [(i, (i + 1) % L) for i in range(L)]

    state = jnp.zeros(microbatches.shape[1:], microbatches.dtype)
    outputs = jnp.zeros(microbatches.shape, jnp.float32)

    def tick(t, carry):
        state, outputs = carry
        # stage 0 ingests microbatch t (while t < M)
        inject = microbatches[jnp.clip(t, 0, M - 1)]
        state = jnp.where(rank == 0, jnp.where(t < M, inject, state), state)
        new_state = stage_fn(stage_params, state)
        # last stage emits microbatch t-(L-1)
        out_idx = t - (L - 1)
        emit = (rank == L - 1) & (out_idx >= 0)
        outputs = jnp.where(
            emit,
            outputs.at[jnp.clip(out_idx, 0, M - 1)].set(
                new_state.astype(jnp.float32)
            ),
            outputs,
        )
        state = lax.ppermute(new_state, axis_name, perm)
        return state, outputs

    # static unroll: T is static (M, L known at trace time); unrolling lets
    # XLA overlap each tick's collective-permute with the next tick's matmuls
    carry = (state, outputs)
    for t in range(T):
        carry = tick(t, carry)
    _, outputs = carry
    # only the last stage wrote; make the result visible on all pp ranks
    return lax.psum(outputs, axis_name)


def pipeline_1f1b_local(fwd_apply: Callable, bwd_apply: Callable, vec,
                        n_micro: int, act_shape, act_dtype,
                        axis_name: str = "pp", rng=None, unroll: int = 1,
                        state=None):
    """Per-device 1F1B micro-batch schedule (call inside shard_map).

    The lockstep-SPMD realization of the reference 1F1B schedule
    (`framework/section_worker.cc:144`): every tick runs one forward slot
    AND one backward slot per stage ("one forward, one backward"), so
    in-flight activations are bounded by the stage count (2L-1 boundary
    activations here, vs M for fill-drain/GPipe) — the defining property of
    1F1B.  Startup: stage r's backward slot idles until its first
    micro-batch's grad returns (the lockstep analog of the reference's
    ``num_stages - stage - 1`` warmup); drain mirrors it at the tail.
    Backward recomputes the stage forward from the saved *input* activation
    (recompute-in-backward), so only stage-boundary tensors are stored.

    fwd_apply(vec, act_in, mb_idx, rng) -> act_out          [all ranks]
    bwd_apply(vec, act_saved, g_in, mb_idx, rng)
        -> (grad_vec, g_out, loss)                           [all ranks]
    Both dispatch on ``lax.axis_index(axis_name)`` internally (lax.switch).

    ``state`` (optional) threads a per-stage mutable-buffer vector (e.g.
    BatchNorm running stats) through the forward slots in micro-batch
    order: fwd_apply then takes a 5th argument and returns
    (act_out, new_state).  Returns (grad_vec_accum, loss_sum[, state]) —
    loss only nonzero on the last stage; psum/scale at the caller.
    """
    L = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    M = n_micro
    D = 2 * L  # residual ring depth; max in-flight = 2(L - r) - 1 <= 2L - 1
    T = M + 2 * L - 1
    fwd_perm = [(i, (i + 1) % L) for i in range(L)]
    bwd_perm = [((i + 1) % L, i) for i in range(L)]
    if rng is None:
        from ..core.framework import make_rng_key

        rng = make_rng_key(0)

    resid = jnp.zeros((D,) + tuple(act_shape), act_dtype)
    rot = jnp.zeros(act_shape, act_dtype)     # incoming activation
    brot = jnp.zeros(act_shape, jnp.float32)  # incoming activation grad
    gacc = jnp.zeros(vec.shape, jnp.float32)
    loss_acc = jnp.zeros((), jnp.float32)
    with_state = state is not None

    def tick(t, carry):
        rot, brot, resid, gacc, loss_acc, st = carry
        f = t - r                      # forward micro-batch at this stage
        b = t - (2 * L - 1) + r        # backward micro-batch at this stage
        f_valid = (f >= 0) & (f < M)
        b_valid = (b >= 0) & (b < M)
        fc = jnp.clip(f, 0, M - 1)
        bc = jnp.clip(b, 0, M - 1)
        # forward slot: per-micro-batch rng must be reproducible at the
        # backward slot's recompute, so key = fold(mb, rank) only
        fkey = jax.random.fold_in(jax.random.fold_in(rng, fc), r)
        if with_state:
            act_out, new_st = fwd_apply(vec, rot, fc, fkey, st)
            st = jnp.where(f_valid, new_st, st)
        else:
            act_out = fwd_apply(vec, rot, fc, fkey)
        resid = jnp.where(f_valid, resid.at[jnp.mod(fc, D)].set(rot), resid)
        act_out = jnp.where(f_valid, act_out,
                            jnp.zeros(act_shape, act_dtype))
        # backward slot
        bkey = jax.random.fold_in(jax.random.fold_in(rng, bc), r)
        saved = resid[jnp.mod(bc, D)]
        gvec, gout, lss = bwd_apply(vec, saved, brot, bc, bkey)
        gacc = gacc + jnp.where(b_valid, gvec.astype(jnp.float32), 0.0)
        loss_acc = loss_acc + jnp.where(b_valid, lss.astype(jnp.float32),
                                        0.0)
        gout = jnp.where(b_valid, gout.astype(jnp.float32),
                         jnp.zeros(act_shape, jnp.float32))
        rot = lax.ppermute(act_out, axis_name, fwd_perm)
        brot = lax.ppermute(gout, axis_name, bwd_perm)
        return rot, brot, resid, gacc, loss_acc, st

    carry = (rot, brot, resid, gacc, loss_acc,
             state if with_state else jnp.zeros((), jnp.float32))
    if unroll >= T:
        for t in range(T):
            carry = tick(t, carry)
    else:
        carry = lax.fori_loop(0, T, tick, carry, unroll=unroll)
    _, _, _, gacc, loss_acc, st = carry
    if with_state:
        return gacc, loss_acc, st
    return gacc, loss_acc


def pipeline_spmd_step(stage_fn: Callable, stacked_params, microbatches, mesh,
                       axis_name: str = "pp", params_pspec=None):
    """Global entry: stacked_params pytree with leading dim = pp size."""
    from jax.sharding import PartitionSpec as P
    from jax import shard_map

    if params_pspec is None:
        params_pspec = jax.tree_util.tree_map(
            lambda a: P(axis_name, *([None] * (a.ndim - 1))), stacked_params
        )

    def local(params, mb):
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        return pipeline_local(stage_fn, params, mb, axis_name)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(params_pspec, P()),
        out_specs=P(),
        check_vma=False,
    )
    return fn(stacked_params, microbatches)
