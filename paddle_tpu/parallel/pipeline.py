"""SPMD pipeline-parallel schedule over the 'pp' mesh axis.

Reference semantics: `SectionWorker::TrainFiles` micro-batch schedules
(`paddle/fluid/framework/section_worker.cc:99` F-then-B, `:144` 1F1B) with
NCCL p2p `send_v2`/`recv_v2` between stages.

TPU-native (SURVEY.md §7 row "send_v2/recv_v2 PP"): homogeneous stage
parameters are STACKED on a leading axis sharded over 'pp', and the whole
microbatch schedule runs inside one jit as a per-device program:
each schedule tick applies the local stage and rotates activations to the
next stage with `lax.ppermute` (collective-permute on ICI).  Reverse-mode AD
through the schedule (ppermute transposes to the reverse ring) yields the
backward pipeline automatically, so fwd+bwd+update is ONE XLA program —
no per-microbatch host scheduling like the reference's SectionWorker loop.
"""
from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_local(stage_fn: Callable, stage_params, microbatches,
                   axis_name: str = "pp"):
    """Per-device schedule body (call inside shard_map).

    stage_fn(params, x) -> y with y.shape == x.shape (homogeneous stages).
    stage_params: this device's stage parameters (leading 'pp' dim removed).
    microbatches: [M, mb, ...] — replicated input; stage 0 ingests them.
    Returns [M, mb, ...] outputs, replicated (psum).
    """
    L = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    M = microbatches.shape[0]
    T = M + L - 1
    perm = [(i, (i + 1) % L) for i in range(L)]

    state = jnp.zeros(microbatches.shape[1:], microbatches.dtype)
    outputs = jnp.zeros(microbatches.shape, jnp.float32)

    def tick(t, carry):
        state, outputs = carry
        # stage 0 ingests microbatch t (while t < M)
        inject = microbatches[jnp.clip(t, 0, M - 1)]
        state = jnp.where(rank == 0, jnp.where(t < M, inject, state), state)
        new_state = stage_fn(stage_params, state)
        # last stage emits microbatch t-(L-1)
        out_idx = t - (L - 1)
        emit = (rank == L - 1) & (out_idx >= 0)
        outputs = jnp.where(
            emit,
            outputs.at[jnp.clip(out_idx, 0, M - 1)].set(
                new_state.astype(jnp.float32)
            ),
            outputs,
        )
        state = lax.ppermute(new_state, axis_name, perm)
        return state, outputs

    # static unroll: T is static (M, L known at trace time); unrolling lets
    # XLA overlap each tick's collective-permute with the next tick's matmuls
    carry = (state, outputs)
    for t in range(T):
        carry = tick(t, carry)
    _, outputs = carry
    # only the last stage wrote; make the result visible on all pp ranks
    return lax.psum(outputs, axis_name)


def pipeline_spmd_step(stage_fn: Callable, stacked_params, microbatches, mesh,
                       axis_name: str = "pp", params_pspec=None):
    """Global entry: stacked_params pytree with leading dim = pp size."""
    from jax.sharding import PartitionSpec as P
    from jax import shard_map

    if params_pspec is None:
        params_pspec = jax.tree_util.tree_map(
            lambda a: P(axis_name, *([None] * (a.ndim - 1))), stacked_params
        )

    def local(params, mb):
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        return pipeline_local(stage_fn, params, mb, axis_name)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(params_pspec, P()),
        out_specs=P(),
        check_vma=False,
    )
    return fn(stacked_params, microbatches)
