"""One partition-rule engine for train AND serve.

Regex partition rules over a parameter pytree — the TPU-idiomatic
pattern for mapping an arbitrary model's params onto a mesh without
hand-annotating every leaf: each rule is ``(regex, PartitionSpec)``,
matched with `re.search` against the '/'-joined tree path of the leaf
('blocks/3/qkv_w', 'w_fc2', ...).  First match wins; scalars and
size-1 leaves are always replicated (sharding a scalar buys nothing
and trips GSPMD's divisibility checks).

`models.gpt_spmd.param_specs` (the train-side conventions: column-split
qkv/fc1, row-split out/fc2 with psum at row outputs, replicated norms)
routes through `match_partition_rules` with `gpt_train_rules`;
`inference.serving.DecodeEngine` shards its per-block serving pytree
with `gpt_serving_rules` — the SAME split geometry, minus the pp/vocab
axes that only exist under training's stacked-layer layout.

Also here because both the costmodel and the multichip tests need it:
`hlo_collectives`, a text parser that reads all-reduce/all-gather/
reduce-scatter/collective-permute shapes (and their byte volumes) out
of optimized HLO — the roofline's interconnect term and the test
suite's "the sharded program really communicates where the math says
it must" assertion share one implementation.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "tree_path_names",
    "match_partition_rules",
    "make_shard_and_gather_fns",
    "gpt_train_rules",
    "gpt_serving_rules",
    "kv_pages_spec",
    "kv_scales_spec",
    "parse_mesh_spec",
    "build_mesh",
    "hlo_collectives",
    "collective_bytes",
]


# ---------------------------------------------------------------------------
# tree paths + rule matching
# ---------------------------------------------------------------------------
def _key_name(k) -> str:
    tu = jax.tree_util
    if isinstance(k, tu.DictKey):
        return str(k.key)
    if isinstance(k, tu.SequenceKey):
        return str(k.idx)
    if isinstance(k, tu.GetAttrKey):
        return str(k.name)
    if isinstance(k, tu.FlattenedIndexKey):
        return str(k.key)
    return str(k)


def tree_path_names(tree, sep: str = "/") -> List[str]:
    """'/'-joined key path of every leaf, in tree_leaves order."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [sep.join(_key_name(k) for k in path) for path, _ in flat]


def match_partition_rules(rules: Sequence[Tuple[str, P]], params):
    """Map a pytree of arrays to a pytree of PartitionSpecs.

    Scalars and size-1 leaves replicate unconditionally; everything
    else takes the spec of the FIRST rule whose regex `re.search`-es
    its '/'-joined path.  A leaf no rule covers raises — a silent
    replicate-by-default would hide a typo'd rule until the profile
    shows a replicated weight eating N× HBM.  (End a rule table with
    ``(".*", P())`` when replicate-by-default is the intent.)
    """
    def get_spec(path_keys, leaf):
        name = "/".join(_key_name(k) for k in path_keys)
        if np.ndim(leaf) == 0 or int(np.prod(np.shape(leaf))) == 1:
            return P()
        for rule, spec in rules:
            if re.search(rule, name):
                return spec
        raise ValueError(f"Partition rule not found for param: {name}")

    return jax.tree_util.tree_map_with_path(get_spec, params)


def make_shard_and_gather_fns(partition_specs, mesh: Mesh):
    """Per-leaf (shard, gather) callables from a spec pytree.

    shard: `jax.device_put` onto the leaf's NamedSharding (committed
    placement — GSPMD propagates from committed inputs, so jitted fns
    need no in_shardings).  gather: device→host `np.asarray` of the
    global value (works on any fully-addressable sharded array).
    """
    shardings = jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), partition_specs,
        is_leaf=lambda x: isinstance(x, P))

    def make_shard(s):
        return lambda x: jax.device_put(x, s)

    def make_gather(s):
        return lambda x: np.asarray(jax.device_get(x))

    shard_fns = jax.tree_util.tree_map(make_shard, shardings)
    gather_fns = jax.tree_util.tree_map(make_gather, shardings)
    return shard_fns, gather_fns


# ---------------------------------------------------------------------------
# GPT rule tables
# ---------------------------------------------------------------------------
def gpt_train_rules() -> List[Tuple[str, P]]:
    """Rules reproducing `gpt_spmd.param_specs` exactly: stacked-block
    params lead with the pp axis, qkv/fc1 column-split over mp (the
    packed qkv axis is head-major so contiguous mp shards hold whole
    heads), out/fc2 row-split (psum at the output), vocab-parallel
    embedding and lm_head."""
    return [
        (r"^wte$", P("mp", None)),
        (r"^wpe$", P()),
        (r"^(ln1|ln2)_(w|b)$", P("pp", None)),
        (r"^w_qkv$", P("pp", None, "mp")),
        (r"^b_qkv$", P("pp", "mp")),
        (r"^w_out$", P("pp", "mp", None)),
        (r"^w_fc1$", P("pp", None, "mp")),
        (r"^b_fc1$", P("pp", "mp")),
        (r"^w_fc2$", P("pp", "mp", None)),
        (r"^(b_out|b_fc2)$", P("pp", None)),
        (r"^lnf_(w|b)$", P()),
        (r"^lm_head$", P(None, "mp")),
    ]


def gpt_serving_rules() -> List[Tuple[str, P]]:
    """Rules for the serving params pytree (`_extract_gpt_params`:
    top-level wte/wpe/lnf/head + per-block 2-D weights, no stacked L
    dim).  Same tensor-parallel geometry as training over the single
    'mp' axis: qkv/fc1 column-split, out/fc2 row-split; biases of
    column-split matmuls shard with their columns; biases of row-split
    matmuls replicate (they add AFTER the cross-chip reduction, once).
    Embeddings, norms and the LM head replicate — decode is
    latency-bound on the per-block matmuls, and a replicated head
    keeps the greedy argmax bit-identical to one chip.  Catch-all
    replicates: serving has no vocab/pp axes to cover.

    serve_weights=int8 engines carry ``*_q``/``*_s`` pairs instead of
    the f32 originals; each pair shards on the SAME geometry — the
    int8 payload like its f32 twin, the per-out-channel scale like the
    column-split bias (it is a vector over the out axis), so the
    dequant multiply stays chip-local.  Row-split weights (out/fc2)
    leave the out axis unsharded, so their scales — and the replicated
    head's pair — fall through to the catch-all."""
    return [
        (r"qkv_w(_q)?$", P(None, "mp")),
        (r"(qkv_b|qkv_w_s)$", P("mp")),
        (r"out_w(_q)?$", P("mp", None)),
        (r"fc1_w(_q)?$", P(None, "mp")),
        (r"(fc1_b|fc1_w_s)$", P("mp")),
        (r"fc2_w(_q)?$", P("mp", None)),
        (r".*", P()),
    ]


def kv_pages_spec() -> P:
    """KV page pool [L, H, n_pages, page, D]: sharded on the head axis
    — each chip holds its head-slice of EVERY page, so page ids stay
    logical and the allocator/block tables stay host-global.  Trailing
    replicated axes are TRIMMED (``P(None, 'mp')``, not the 5-element
    form): jit reconstructs output shardings from HLO in the trimmed
    form, and the donated pool round-trips executable-output ->
    next-step-input — an untrimmed construction-time spec would differ
    from the step's own output spec and retrace the warm cache on the
    second step."""
    return P(None, "mp")


def kv_scales_spec() -> P:
    """int8 KV page scales [L, H, n_pages]: head axis follows the
    pages (trailing replicated axes trimmed, as in `kv_pages_spec`)."""
    return P(None, "mp")


# ---------------------------------------------------------------------------
# mesh specs
# ---------------------------------------------------------------------------
def parse_mesh_spec(spec: str) -> List[Tuple[str, int]]:
    """'mp=2' / 'dp=2,mp=4' -> ordered [(axis, size), ...].  Raises on
    malformed axes or non-positive sizes; an empty string is an error
    here (callers treat empty as mesh-off BEFORE parsing)."""
    out: List[Tuple[str, int]] = []
    if not spec or not spec.strip():
        raise ValueError("empty mesh spec")
    for part in spec.split(","):
        m = re.fullmatch(r"\s*([A-Za-z_]\w*)\s*=\s*(\d+)\s*", part)
        if not m:
            raise ValueError(
                f"bad mesh spec {spec!r}: expected 'axis=N[,axis=N...]'")
        name, n = m.group(1), int(m.group(2))
        if n <= 0:
            raise ValueError(f"bad mesh spec {spec!r}: {name}={n}")
        if any(name == a for a, _ in out):
            raise ValueError(f"bad mesh spec {spec!r}: duplicate axis {name}")
        out.append((name, n))
    return out


def build_mesh(spec: str, devices=None) -> Mesh:
    """Mesh from a spec string over the first prod(sizes) devices."""
    axes = parse_mesh_spec(spec)
    names = tuple(a for a, _ in axes)
    sizes = tuple(n for _, n in axes)
    need = int(np.prod(sizes))
    devs = list(jax.devices() if devices is None else devices)
    if need > len(devs):
        raise ValueError(
            f"mesh spec {spec!r} needs {need} devices, have {len(devs)}")
    return Mesh(np.asarray(devs[:need]).reshape(sizes), names)


# ---------------------------------------------------------------------------
# HLO collective accounting
# ---------------------------------------------------------------------------
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}

# one HLO instruction: `%name = <shapes> opcode(...)`.  Async pairs
# (`all-reduce-start`/`-done`) would double-count; only the non-`-done`
# half carries the transfer.
_COLL_RE = re.compile(
    r"=\s*(?P<shapes>.*?)\s*"
    r"(?P<op>all-reduce|all-gather|reduce-scatter|collective-permute"
    r"|all-to-all)(?P<suffix>-start)?\(")
_SHAPE_RE = re.compile(r"([a-z]\d*|pred|bf16)\[([0-9,]*)\]")


def _shape_bytes(shapes_text: str) -> float:
    total = 0.0
    for dt, dims in _SHAPE_RE.findall(shapes_text):
        size = _DTYPE_BYTES.get(dt)
        if size is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * size
    return total


def hlo_collectives(hlo_text: str) -> Dict[str, Dict[str, Any]]:
    """Per-opcode {count, bytes} read from (optimized) HLO text.

    Bytes are the instruction's OUTPUT shape sizes — the volume the
    interconnect moves per call site, the quantity the roofline's ICI
    term divides by link bandwidth.  `-done` halves of async pairs are
    skipped (the `-start` already counted the transfer)."""
    out: Dict[str, Dict[str, Any]] = {}
    for line in hlo_text.splitlines():
        if "-done" in line:
            continue
        m = _COLL_RE.search(line)
        if not m:
            continue
        op = m.group("op")
        row = out.setdefault(op, {"count": 0, "bytes": 0.0})
        row["count"] += 1
        row["bytes"] += _shape_bytes(m.group("shapes"))
    return out


def collective_bytes(hlo_text: str) -> float:
    """Total bytes moved by collectives in one HLO program."""
    return float(sum(r["bytes"] for r in hlo_collectives(hlo_text).values()))
