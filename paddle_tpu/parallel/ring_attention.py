"""Ring attention — sequence/context parallelism.

Net-new capability (reference has NO SP/CP — SURVEY.md §2.3 row "Sequence/
context parallel": the TPU build must add it).  Design: Q/K/V are sharded
over the 'sp' mesh axis on the sequence dim; each device holds its local Q
block and rotates K/V blocks around the ring with `lax.ppermute`, folding
each visiting block into a numerically-stable online softmax (flash-style
running max / running sum), so attention over a sequence of length S uses
O(S/sp) memory per chip and the K/V transfers ride the ICI ring concurrently
with compute.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def _block_attn(q, k, v, scale, mask):
    # q:[B,H,Sq,D] k,v:[B,H,Sk,D]; returns (out_unnorm, row_max, row_sum)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    logits = logits.astype(jnp.float32)
    if mask is not None:
        logits = jnp.where(mask, logits, -1e30)
    m = jnp.max(logits, axis=-1)
    p = jnp.exp(logits - m[..., None])
    s = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)
    return o, m, s


def _use_flash_blocks(q, scale):
    """Whether the Pallas flash kernel should compute each ring block —
    the single shared gate (`pallas_attention_wanted`) + block policy
    (`pick_blocks`) and a static-scale requirement (the kernel bakes
    scale as a compile-time constant)."""
    from ..ops.pallas.flash_attention import (pallas_attention_wanted,
                                              pick_blocks)

    if pick_blocks(q.shape[-2], q.shape[-2]) is None or q.shape[-1] % 64:
        return False
    if not isinstance(scale, (int, float)):
        return False  # traced scale can't be baked into the kernel
    return pallas_attention_wanted(q.shape[-2])


def _flash_or_skip(q, k, v, scale, causal, rank, src):
    """Causal ring block via flash: a block strictly below the diagonal
    (src < rank) is fully visible, the diagonal block (src == rank) is
    causal, and a block strictly above (src > rank) contributes nothing
    — selected with lax.cond since rank/src are traced."""
    if not causal:
        return _flash_block(q, k, v, scale, False)
    b, h, sq = q.shape[0], q.shape[1], q.shape[2]

    def masked():
        return (jnp.zeros(q.shape, jnp.float32),
                jnp.full((b, h, sq), -jnp.inf, jnp.float32),
                jnp.zeros((b, h, sq), jnp.float32))

    return lax.cond(
        src > rank, masked,
        lambda: lax.cond(src == rank,
                         lambda: _flash_block(q, k, v, scale, True),
                         lambda: _flash_block(q, k, v, scale, False)))


def _flash_block(q, k, v, scale, causal):
    """One ring block via the Pallas flash kernel.  The kernel returns the
    NORMALIZED block output plus the row logsumexp; that maps onto the
    online-softmax carry as (o_unnorm=out, m=lse, l=1), since
    exp(logits - lse) sums to exactly 1.  Differentiable: the custom VJP
    recomputes the block in composed form (the same O(S_local^2) the
    pre-flash ring used, but only during backward)."""
    out, lse = _flash_block_diff(q, k, v, causal, float(scale))
    b, h, sq, _ = q.shape
    return out, lse, jnp.ones((b, h, sq), jnp.float32)


def _composed_block(q, k, v, causal, scale):
    """(normalized out f32, lse) of one block — delegates to the ONE
    composed attention definition (`_composed_attention`), so the VJP
    ground truth can never drift from the single-device reference."""
    from ..ops.pallas.flash_attention import _composed_attention

    out, lse = _composed_attention(q, k, v, None, causal, scale,
                                   want_lse=True)
    return out.astype(jnp.float32), lse


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_block_diff(q, k, v, causal, scale):
    from ..ops.pallas.flash_attention import _pallas_forward, pick_blocks

    bq, bk = pick_blocks(q.shape[-2], k.shape[-2])
    out, lse = _pallas_forward(q, k, v, causal, scale, bq, bk)
    b, h, sq, _ = q.shape
    return out.astype(jnp.float32), lse.reshape(b, h, sq)


def _flash_block_diff_fwd(q, k, v, causal, scale):
    return _flash_block_diff(q, k, v, causal, scale), (q, k, v)


def _flash_block_diff_bwd(causal, scale, res, cots):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda a, b, c: _composed_block(a, b, c, causal, scale), q, k, v)
    return vjp(cots)


_flash_block_diff.defvjp(_flash_block_diff_fwd, _flash_block_diff_bwd)


def ring_attention_local(q, k, v, axis_name: str = "sp", causal: bool = False,
                         scale=None):
    """Per-device body; call inside shard_map with q/k/v sharded on the seq
    dim over `axis_name`.  q,k,v: [B, H, S_local, D]."""
    import math

    n = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    s_local = q.shape[2]
    use_flash = _use_flash_blocks(q, s)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(i, carry):
        o_acc, m_acc, l_acc, k_cur, v_cur = carry
        # K/V block currently held came from rank (rank - i) mod n
        src = (rank - i) % n
        if use_flash:
            o_blk, m_blk, l_blk = _flash_or_skip(q, k_cur, v_cur, s,
                                                 causal, rank, src)
        else:
            if causal:
                q_pos = rank * s_local + jnp.arange(s_local)
                k_pos = src * s_local + jnp.arange(s_local)
                mask = q_pos[:, None] >= k_pos[None, :]
                mask = mask[None, None]
            else:
                mask = None
            o_blk, m_blk, l_blk = _block_attn(q, k_cur, v_cur, s, mask)
        m_new = jnp.maximum(m_acc, m_blk)
        alpha = jnp.exp(m_acc - m_new)
        beta = jnp.exp(m_blk - m_new)
        o_acc = o_acc * alpha[..., None].astype(o_acc.dtype) + \
            o_blk * beta[..., None].astype(o_blk.dtype)
        l_acc = l_acc * alpha + l_blk * beta
        if i < n - 1:
            # N-1 rotations suffice: after the last block is consumed a
            # further rotation's result is never read (round-4 comm fix
            # — also what the schedule-accounting test pins)
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
        return o_acc, m_new, l_acc, k_cur, v_cur

    b, h = q.shape[0], q.shape[1]
    o0 = jnp.zeros(q.shape, jnp.float32)
    m0 = jnp.full((b, h, s_local), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, s_local), jnp.float32)
    o, m, l, _, _ = _unrolled(step, n, (o0, m0, l0, k, v))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def _unrolled(step, n, carry):
    # unrolled ring (n is a static mesh size; unrolling lets XLA overlap the
    # ppermute of step i+1 with the matmuls of step i)
    for i in range(n):
        carry = step(i, carry)
    return carry


def ring_attention(q, k, v, mesh, axis_name: str = "sp", causal: bool = False,
                   scale=None):
    """Global entry: q,k,v are global arrays [B,H,S,D]; returns attention
    computed with the ring schedule, sharded over `axis_name` on S."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax import shard_map

    spec = P(None, None, axis_name, None)
    fn = shard_map(
        partial(ring_attention_local, axis_name=axis_name, causal=causal,
                scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
