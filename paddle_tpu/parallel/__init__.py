"""Low-level SPMD parallel engine: mesh schedules implemented with
`shard_map` + explicit XLA collectives (ppermute/all_gather/psum).

This package holds the kernels that need explicit per-device programs rather
than GSPMD annotations: the 1F1B pipeline schedule and ring attention
(sequence parallelism).  fleet routes to these when pp>1 / sp>1.
"""
from .partition import (build_mesh, collective_bytes,  # noqa: F401
                        gpt_serving_rules, gpt_train_rules, hlo_collectives,
                        make_shard_and_gather_fns, match_partition_rules,
                        parse_mesh_spec)
from .pipeline import pipeline_spmd_step  # noqa: F401
from .ring_attention import ring_attention  # noqa: F401
