"""Low-level SPMD parallel engine: mesh schedules implemented with
`shard_map` + explicit XLA collectives (ppermute/all_gather/psum).

This package holds the kernels that need explicit per-device programs rather
than GSPMD annotations: the 1F1B pipeline schedule and ring attention
(sequence parallelism).  fleet routes to these when pp>1 / sp>1.
"""
from .pipeline import pipeline_spmd_step  # noqa: F401
from .ring_attention import ring_attention  # noqa: F401
