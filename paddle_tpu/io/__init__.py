"""`paddle.io`: Dataset / BatchSampler / DataLoader.

Reference: `python/paddle/fluid/reader.py:146` (DataLoader),
`fluid/dataloader/` (Dataset, BatchSampler, multiprocess workers over
shared-memory LoDTensors, `dataloader_iter.py:97,248`).

TPU-native: workers produce numpy batches on host threads (the GIL is
released inside numpy / jax device_put), and device transfer happens once
per batch (`jax.device_put`), optionally double-buffered so host→HBM copy
overlaps step compute — the role of the reference's `buffered_reader.cc`.
"""
from __future__ import annotations

import itertools
import queue
import threading
from typing import Iterable, List, Optional

import numpy as np

from ..core import framework
from ..core.tensor import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = [t.numpy() if isinstance(t, Tensor) else np.asarray(t)
                        for t in tensors]

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = indices

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative = np.cumsum([len(d) for d in self.datasets])

    def __len__(self):
        return int(self.cumulative[-1])

    def __getitem__(self, idx):
        d = int(np.searchsorted(self.cumulative, idx, side="right"))
        prev = 0 if d == 0 else int(self.cumulative[d - 1])
        return self.datasets[d][idx - prev]


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __iter__(self):
        for d in self.datasets:
            yield from d


def random_split(dataset, lengths, generator=None):
    idx = np.random.permutation(len(dataset))
    out, start = [], 0
    for ln in lengths:
        out.append(Subset(dataset, idx[start:start + ln].tolist()))
        start += ln
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self.num_samples = num_samples or len(data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """reference `fluid/dataloader/batch_sampler.py` DistributedBatchSampler."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import get_rank, get_world_size

        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        import math

        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices = np.concatenate([indices, indices[: self.total_size - n]])
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices:
            batch.append(int(idx))
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        return tuple(default_collate_fn([b[i] for b in batch])
                     for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, Tensor):
        return np.stack([s.numpy() for s in batch])
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    return np.asarray(batch)


class DataLoader:
    """Reference `paddle.io.DataLoader` (`fluid/reader.py:146`).

    num_workers>0 uses a thread pool (numpy releases the GIL; JAX is not
    fork-safe, so threads replace the reference's forked workers) with a
    bounded prefetch queue — the analog of the reference's multiprocess
    workers + `buffered_reader.cc` double buffering.
    """

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, multiprocess_mode="process"):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(2, prefetch_factor)
        self.use_shared_memory = use_shared_memory
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.persistent_workers = persistent_workers
        # "process": spawned workers + shm transport (reference
        # dataloader_iter.py:248 fork workers); "thread": GIL-bound pool
        # (fine for numpy-heavy transforms, zero startup cost)
        self.multiprocess_mode = multiprocess_mode
        self._pool = None  # persistent worker pool
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        elif self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)
            self.batch_size = batch_size

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len")
        return len(self.batch_sampler)

    def _fetch(self, indices):
        return self.collate_fn([self.dataset[i] for i in indices])

    def _to_tensors(self, batch):
        if isinstance(batch, (tuple, list)):
            return tuple(self._to_tensors(b) for b in batch)
        if isinstance(batch, dict):
            return {k: self._to_tensors(v) for k, v in batch.items()}
        return Tensor(batch)

    def __iter__(self):
        if self._iterable_mode:
            yield from self._iter_iterable()
            return
        if self.num_workers <= 0:
            for indices in self.batch_sampler:
                yield self._to_tensors(self._fetch(indices))
            return
        if self.multiprocess_mode == "process":
            try:
                pool = self._ensure_pool()
            except Exception as e:
                # unpicklable dataset / restricted platform: degrade to the
                # thread pool rather than failing the input pipeline
                import warnings

                warnings.warn(
                    f"multiprocess DataLoader unavailable ({e!r}); "
                    "falling back to threads", stacklevel=2)
                pool = None
            if pool is not None:
                # a worker that dies before producing ANYTHING is a spawn
                # bootstrap failure (e.g. guard-less __main__ script), not
                # a data error — data errors are reported through the
                # result queue by a live worker.  Only that case degrades
                # to threads; after the first batch, errors propagate.
                mp_iter = self._iter_multiprocess(pool)
                try:
                    first = next(mp_iter)
                except StopIteration:
                    return
                except RuntimeError as e:
                    if "exited unexpectedly" not in str(e):
                        raise
                    import warnings

                    warnings.warn(
                        "DataLoader worker processes failed to start "
                        "(is the training script missing an `if __name__"
                        " == '__main__'` guard?); falling back to "
                        "threads", stacklevel=2)
                    self._pool = None
                else:
                    yield first
                    yield from mp_iter
                    return
        yield from self._iter_threaded()

    # -- multiprocess path (reference dataloader_iter.py:248) ---------------
    def _ensure_pool(self):
        if self._pool is not None and self._pool.alive():
            return self._pool
        self._pool = _WorkerPool(self.dataset, self.collate_fn,
                                 self.num_workers, self.use_shared_memory,
                                 self.worker_init_fn)
        return self._pool

    def _iter_multiprocess(self, pool):
        from .worker import _discard_payload, _unpack

        indices_list = list(self.batch_sampler)
        depth = self.num_workers * self.prefetch_factor
        gen = pool.start_epoch()  # stale results from a truncated prior
        sent = 0                  # epoch carry this tag and are discarded
        for i in range(min(depth, len(indices_list))):
            pool.send(gen, i, indices_list[i])
            sent += 1
        pending = {}
        # timeout=0 = wait indefinitely (reference semantics); worker
        # death still raises via the watchdog inside recv()
        timeout = self.timeout if self.timeout and self.timeout > 0 \
            else None
        try:
            for want in range(len(indices_list)):
                while want not in pending:
                    rgen, bid, payload, err = pool.recv(timeout)
                    if rgen != gen:
                        _discard_payload(payload)  # unlink stale epoch shm
                        continue
                    if err is not None:
                        raise RuntimeError(
                            f"DataLoader worker failed:\n{err}")
                    pending[bid] = payload
                payload = pending.pop(want)
                if sent < len(indices_list):
                    pool.send(gen, sent, indices_list[sent])
                    sent += 1
                yield self._to_tensors(_unpack(payload))
        finally:
            # unlink shm of anything buffered but never consumed
            for payload in pending.values():
                _discard_payload(payload)
            if not self.persistent_workers:
                pool.shutdown()
                self._pool = None

    def __del__(self):
        try:
            if self._pool is not None:
                self._pool.shutdown()
        except Exception:
            pass

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield self._to_tensors(self.collate_fn(batch))
                batch = []
        if batch and not getattr(self, "drop_last", False):
            yield self._to_tensors(self.collate_fn(batch))

    def _iter_threaded(self):
        from concurrent.futures import ThreadPoolExecutor

        indices_list = list(self.batch_sampler)
        depth = self.num_workers * self.prefetch_factor
        with ThreadPoolExecutor(max_workers=self.num_workers) as pool:
            futures = []
            it = iter(indices_list)
            for _ in range(min(depth, len(indices_list))):
                futures.append(pool.submit(self._fetch, next(it)))
            i = 0
            while futures:
                fut = futures.pop(0)
                batch = fut.result()
                nxt = next(it, None)
                if nxt is not None:
                    futures.append(pool.submit(self._fetch, nxt))
                yield self._to_tensors(batch)


class _WorkerPool:
    """Spawned worker processes + queues (reference `dataloader_iter.py`
    `_DataLoaderIterMultiProcess`: per-worker index queues, one shared
    result queue, liveness watchdog)."""

    def __init__(self, dataset, collate_fn, num_workers, use_shared_memory,
                 worker_init_fn):
        import multiprocessing as mp
        import os

        from .worker import _worker_loop

        ctx = mp.get_context("spawn")  # fork is unsafe once PJRT is live
        self.num_workers = num_workers
        self.index_queues = [ctx.Queue() for _ in range(num_workers)]
        self.result_queue = ctx.Queue()
        self.workers = []
        seed = np.random.randint(0, 2 ** 31 - 1)
        # spawned children must never touch the trainer's TPU: pin their
        # jax (imported by sitecustomize at interpreter start) to CPU
        saved = os.environ.get("JAX_PLATFORMS")
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            for wid in range(num_workers):
                p = ctx.Process(
                    target=_worker_loop,
                    args=(dataset, collate_fn, self.index_queues[wid],
                          self.result_queue, wid, num_workers,
                          use_shared_memory, worker_init_fn, seed),
                    daemon=True)
                p.start()  # pickles args here: unpicklables raise now
                self.workers.append(p)
        finally:
            if saved is None:
                os.environ.pop("JAX_PLATFORMS", None)
            else:
                os.environ["JAX_PLATFORMS"] = saved

    def alive(self):
        return bool(self.workers) and all(p.is_alive()
                                          for p in self.workers)

    def start_epoch(self) -> int:
        """Bump the result generation: anything a truncated previous epoch
        left in flight is identifiable (and unlinked) instead of being
        mistaken for this epoch's batches."""
        self.generation = getattr(self, "generation", 0) + 1
        return self.generation

    def send(self, gen, batch_id, indices):
        self.index_queues[batch_id % self.num_workers].put(
            (gen, batch_id, list(indices)))

    def recv(self, timeout):
        """Result-queue get with a liveness watchdog (reference
        worker-watchdog): a dead worker must raise, not hang forever.
        timeout=None waits indefinitely (but still watches liveness)."""
        import queue as pyqueue
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                return self.result_queue.get(timeout=1.0)
            except pyqueue.Empty:
                dead = [p.pid for p in self.workers if not p.is_alive()]
                if dead:
                    raise RuntimeError(
                        f"DataLoader worker(s) {dead} exited "
                        "unexpectedly") from None
                if deadline is not None and time.monotonic() > deadline:
                    raise RuntimeError(
                        f"DataLoader timed out after {timeout}s waiting "
                        "for a worker batch") from None

    def shutdown(self):
        import queue as pyqueue

        from .worker import _discard_payload

        for q in self.index_queues:
            try:
                q.put(None)
            except Exception:
                pass
        for p in self.workers:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        self.workers = []
        # workers are gone: anything still queued can never be consumed —
        # unlink its shared memory before dropping the queue
        while True:
            try:
                item = self.result_queue.get_nowait()
            except (pyqueue.Empty, OSError, ValueError):
                break
            if item and len(item) == 4:
                _discard_payload(item[2])


def get_worker_info():
    from .worker import get_worker_info as _gwi

    return _gwi()
