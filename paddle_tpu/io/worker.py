"""Multiprocess DataLoader workers with shared-memory batch transport.

Reference: `fluid/dataloader/worker.py` + the fork-worker loop in
`fluid/dataloader/dataloader_iter.py:248`, whose tensors travel through
mmap shared memory (`memory/allocation/mmap_allocator.cc`).  TPU-native
realization: workers are SPAWNED processes (fork is unsafe once JAX/PJRT
is initialized) that place collated numpy batches into POSIX shared-memory
segments (`multiprocessing.shared_memory`, the stdlib's mmap-backed shm);
the parent maps each segment, copies out, and unlinks.  Workers run with
``JAX_PLATFORMS=cpu`` pinned in their environment so a spawned child can
never grab the TPU the trainer owns.
"""
from __future__ import annotations

import os
import traceback
from typing import Optional

import numpy as np

_WORKER_INFO = None


class WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


def get_worker_info() -> Optional[WorkerInfo]:
    return _WORKER_INFO


_SHM_MIN_BYTES = 4096  # below this, queue pickling beats a segment


def _pack(obj, shms: list, use_shared_memory: bool):
    """Replace large ndarrays with shared-memory descriptors."""
    if isinstance(obj, tuple):
        return tuple(_pack(o, shms, use_shared_memory) for o in obj)
    if isinstance(obj, list):
        return [_pack(o, shms, use_shared_memory) for o in obj]
    if isinstance(obj, dict):
        return {k: _pack(v, shms, use_shared_memory) for k, v in obj.items()}
    if isinstance(obj, np.ndarray) and use_shared_memory and \
            obj.nbytes >= _SHM_MIN_BYTES:
        from multiprocessing import shared_memory

        seg = shared_memory.SharedMemory(create=True, size=obj.nbytes)
        view = np.ndarray(obj.shape, obj.dtype, buffer=seg.buf)
        view[...] = obj
        shms.append(seg)
        return ("__shm__", seg.name, obj.shape, str(obj.dtype))
    return obj


def _unpack(obj):
    if isinstance(obj, tuple):
        if len(obj) == 4 and obj[0] == "__shm__":
            from multiprocessing import shared_memory

            _, name, shape, dtype = obj
            seg = shared_memory.SharedMemory(name=name)
            try:
                arr = np.ndarray(shape, np.dtype(dtype),
                                 buffer=seg.buf).copy()
            finally:
                seg.close()
                seg.unlink()
            return arr
        return tuple(_unpack(o) for o in obj)
    if isinstance(obj, list):
        return [_unpack(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _unpack(v) for k, v in obj.items()}
    return obj


def _discard_payload(payload):
    """Unlink any shared-memory segments referenced by an unconsumed
    payload (stale generation, error teardown, shutdown drain) — dropped
    descriptors would otherwise leak /dev/shm until reboot."""
    if payload is None:
        return
    try:
        _unpack(payload)  # maps, copies (cheap relative to a leak), unlinks
    except Exception:
        pass


def _worker_loop(dataset, collate_fn, index_queue, result_queue, worker_id,
                 num_workers, use_shared_memory, worker_init_fn, seed):
    """Runs in the spawned child: pull index lists, push packed batches.
    Mirrors the reference worker loop incl. per-worker seeding and
    exception transport back to the parent."""
    global _WORKER_INFO
    _WORKER_INFO = WorkerInfo(worker_id, num_workers, dataset)
    np.random.seed((int(seed) + worker_id) % (2 ** 32))
    if worker_init_fn is not None:
        try:
            worker_init_fn(worker_id)
        except Exception:
            result_queue.put((0, None, None, traceback.format_exc()))
            return
    while True:
        item = index_queue.get()
        if item is None:  # sentinel: clean shutdown
            break
        gen, bid, indices = item
        try:
            batch = collate_fn([dataset[i] for i in indices])
            shms = []
            payload = _pack(batch, shms, use_shared_memory)
            result_queue.put((gen, bid, payload, None))
            # child closes its mapping; the parent unlinks after copying.
            # Unregister from this process's resource tracker so the
            # child's exit doesn't double-unlink / warn about segments the
            # parent owns now.
            for seg in shms:
                try:
                    from multiprocessing import resource_tracker

                    resource_tracker.unregister(seg._name, "shared_memory")
                except Exception:
                    pass
                seg.close()
        except Exception:
            result_queue.put((gen, bid, None, traceback.format_exc()))
