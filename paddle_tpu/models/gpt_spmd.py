"""GPT hybrid-parallel SPMD train step: dp x pp x sp x mp in ONE program.

This is the TPU-native counterpart of the reference's 4-D hybrid runs
(`fleet/base/topology.py` HybridCommunicateGroup + sharding/tp/pp meta
optimizers + SectionWorker 1F1B, SURVEY.md §2.3): a single `shard_map` over
the ('dp','pp','sp','mp') mesh whose per-device program implements

* data parallel   — tokens sharded over 'dp'; grads psum over 'dp'
* tensor parallel — qkv/mlp column+row splits over 'mp' with psum at row
                    outputs; vocab-parallel embedding + cross-entropy
                    (reference `c_softmax_with_cross_entropy`)
* sequence parallel — activations sharded over 'sp' on the seq dim; ring
                    attention rotates K/V blocks over the 'sp' ring
                    (net-new vs reference, SURVEY.md §5 long-context)
* pipeline parallel — homogeneous blocks stacked over 'pp'; microbatch
                    schedule rotates activations with collective-permute
                    (reference `section_worker.cc` schedules); stage 0
                    embeds tokens, the last stage computes the loss
* optimizer update — SGD applied to local shards (ZeRO-flavored: each
                    device updates only the slice it owns)

jax.grad differentiates the entire per-device schedule; the transpose of
ppermute is the reverse ring, which IS the backward pipeline pass.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Dict, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.partition import gpt_train_rules, match_partition_rules
from ..parallel.ring_attention import ring_attention_local
from .gpt import GPTConfig


# ---------------------------------------------------------------------------
# parameter pytree (global logical shapes) + PartitionSpecs
# ---------------------------------------------------------------------------
# parameter names in the train pytree; specs come from the shared
# regex rule engine (parallel.partition) so train and serve derive
# their tensor-parallel geometry from ONE rule table convention:
# vocab-parallel embedding, pp-stacked blocks, column-split qkv/fc1,
# row-split out/fc2, replicated norms.
_PARAM_NAMES = (
    "wte", "wpe",
    "ln1_w", "ln1_b", "w_qkv", "b_qkv", "w_out", "b_out",
    "ln2_w", "ln2_b", "w_fc1", "b_fc1", "w_fc2", "b_fc2",
    "lnf_w", "lnf_b", "lm_head",
)


def param_specs(cfg: GPTConfig) -> Dict[str, P]:
    # 2-D placeholders: match_partition_rules replicates scalars and
    # size-1 leaves, so specs must be derived from names alone, not
    # from real (possibly degenerate) shapes
    shapes = {k: jnp.zeros((2, 2)) for k in _PARAM_NAMES}
    return match_partition_rules(gpt_train_rules(), shapes)


def init_params(cfg: GPTConfig, key) -> Dict[str, jnp.ndarray]:
    H, L, F, V, S = (cfg.hidden_size, cfg.num_layers, cfg.intermediate_size,
                     cfg.vocab_size, cfg.max_seq_len)
    ks = jax.random.split(key, 8)
    std = 0.02
    rstd = std / math.sqrt(2 * L)
    return {
        "wte": jax.random.normal(ks[0], (V, H), jnp.float32) * std,
        "wpe": jax.random.normal(ks[1], (S, H), jnp.float32) * std,
        "ln1_w": jnp.ones((L, H)), "ln1_b": jnp.zeros((L, H)),
        "w_qkv": jax.random.normal(ks[2], (L, H, 3 * H)) * std,
        "b_qkv": jnp.zeros((L, 3 * H)),
        "w_out": jax.random.normal(ks[3], (L, H, H)) * rstd,
        "b_out": jnp.zeros((L, H)),
        "ln2_w": jnp.ones((L, H)), "ln2_b": jnp.zeros((L, H)),
        "w_fc1": jax.random.normal(ks[4], (L, H, F)) * std,
        "b_fc1": jnp.zeros((L, F)),
        "w_fc2": jax.random.normal(ks[5], (L, F, H)) * rstd,
        "b_fc2": jnp.zeros((L, H)),
        "lnf_w": jnp.ones((H,)), "lnf_b": jnp.zeros((H,)),
        "lm_head": jax.random.normal(ks[6], (H, V)) * std,
    }


def _layernorm(x, w, b, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, -1, keepdims=True)
    var = jnp.var(x32, -1, keepdims=True)
    return ((x32 - mu) * lax.rsqrt(var + eps)).astype(x.dtype) * w + b


def _block(x, p, li, num_heads_local, compute_dtype):
    """One transformer block on local shards. x: [b, s_local, H]."""
    b, s, H = x.shape
    y = _layernorm(x, p["ln1_w"][li], p["ln1_b"][li])
    qkv = (y.astype(compute_dtype) @ p["w_qkv"][li].astype(compute_dtype)
           ) + p["b_qkv"][li].astype(compute_dtype)
    # local: [b, s, 3*H/mp] -> [b, heads_local, s, d] x3.  The packed qkv
    # axis is HEAD-MAJOR ((heads, 3, d)) so that contiguous mp shards hold
    # whole heads — sharding a (3, heads, d)-ordered axis would hand each
    # rank fragments of q, k and v.
    hl = num_heads_local
    head_dim = qkv.shape[-1] // (3 * hl)
    qkv = qkv.reshape(b, s, hl, 3, head_dim).transpose(3, 0, 2, 1, 4)
    q, k, v = qkv[0], qkv[1], qkv[2]
    attn = ring_attention_local(q, k, v, axis_name="sp", causal=True)
    attn = attn.transpose(0, 2, 1, 3).reshape(b, s, hl * head_dim)
    out = attn @ p["w_out"][li].astype(compute_dtype)
    out = lax.psum(out, "mp") + p["b_out"][li].astype(compute_dtype)
    x = x + out.astype(x.dtype)

    y = _layernorm(x, p["ln2_w"][li], p["ln2_b"][li])
    h1 = jax.nn.gelu(
        y.astype(compute_dtype) @ p["w_fc1"][li].astype(compute_dtype)
        + p["b_fc1"][li].astype(compute_dtype), approximate=True)
    h2 = h1 @ p["w_fc2"][li].astype(compute_dtype)
    h2 = lax.psum(h2, "mp") + p["b_fc2"][li].astype(compute_dtype)
    return x + h2.astype(x.dtype)


def _embed(tokens, p, sp_rank, s_local, compute_dtype):
    """Vocab-parallel embedding + position (local seq positions)."""
    V_local = p["wte"].shape[0]
    mp_rank = lax.axis_index("mp")
    lo = mp_rank * V_local
    local_tok = tokens - lo
    in_shard = (local_tok >= 0) & (local_tok < V_local)
    safe = jnp.clip(local_tok, 0, V_local - 1)
    emb = jnp.where(in_shard[..., None], p["wte"][safe], 0.0)
    emb = lax.psum(emb, "mp")
    pos = sp_rank * s_local + jnp.arange(s_local)
    return (emb + p["wpe"][pos]).astype(compute_dtype)


def _vocab_parallel_ce(logits, labels):
    """Cross entropy over mp-sharded vocab (reference
    c_softmax_with_cross_entropy_op semantics). logits: [b, s, V/mp]."""
    logits = logits.astype(jnp.float32)
    V_local = logits.shape[-1]
    mp_rank = lax.axis_index("mp")
    lo = mp_rank * V_local
    # stability max is gradient-free (pmax has no JVP rule, so stop_gradient
    # must be applied to its INPUT — zero tangents skip the missing rule; as a
    # constant shift it cancels in the softmax anyway)
    m = lax.pmax(lax.stop_gradient(jnp.max(logits, -1)), "mp")
    lse = jnp.log(lax.psum(jnp.sum(jnp.exp(logits - m[..., None]), -1), "mp")) + m
    local_lab = labels - lo
    in_shard = (local_lab >= 0) & (local_lab < V_local)
    safe = jnp.clip(local_lab, 0, V_local - 1)
    tgt = jnp.where(in_shard,
                    jnp.take_along_axis(logits, safe[..., None], -1)[..., 0],
                    0.0)
    tgt = lax.psum(tgt, "mp")
    return lse - tgt  # [b, s] per-token nll


def build_spmd_train_step(cfg: GPTConfig, mesh: Mesh, num_micro: int = 1,
                          lr: float = 1e-3, compute_dtype=jnp.bfloat16):
    """Returns jitted step(params, tokens, labels) -> (loss, new_params).

    tokens/labels: global [B, S] int32, B % (dp*num_micro) == 0,
    S % sp == 0, heads % mp == 0, L % pp == 0, V % mp == 0.
    """
    pp = int(mesh.shape["pp"])
    mp = int(mesh.shape["mp"])
    layers_per_stage = cfg.num_layers // pp
    heads_local = cfg.num_heads // mp
    specs = param_specs(cfg)

    def device_fn(params, tokens, labels):
        # local views: tokens [B/dp, S/sp]
        pp_rank = lax.axis_index("pp")
        sp_rank = lax.axis_index("sp")
        s_local = tokens.shape[1]
        M = num_micro
        mb = tokens.shape[0] // M
        micro_tok = tokens.reshape(M, mb, s_local)
        micro_lab = labels.reshape(M, mb, s_local)
        n_tokens_global = (tokens.shape[0] * s_local
                           * int(lax.axis_size("dp")) * int(lax.axis_size("sp")))

        def loss_fn(prm):
            def stage(state):
                for li in range(layers_per_stage):
                    state = _block(state, prm, li, heads_local, compute_dtype)
                return state

            perm = [(i, (i + 1) % pp) for i in range(pp)]
            T = M + pp - 1
            state = jnp.zeros((mb, s_local, cfg.hidden_size), compute_dtype)
            total = jnp.zeros((), jnp.float32)
            for t in range(T):
                tok_t = micro_tok[min(t, M - 1)]
                embedded = _embed(tok_t, prm, sp_rank, s_local, compute_dtype)
                state = jnp.where((pp_rank == 0) & (t < M), embedded, state)
                state = stage(state)
                # last stage emits loss for microbatch t-(pp-1)
                o = t - (pp - 1)
                xf = _layernorm(state, prm["lnf_w"], prm["lnf_b"])
                logits = xf.astype(compute_dtype) @ prm["lm_head"].astype(compute_dtype)
                nll = _vocab_parallel_ce(logits, micro_lab[max(min(o, M - 1), 0)])
                emit = (pp_rank == pp - 1) & (o >= 0)
                total = total + jnp.where(emit, jnp.sum(nll), 0.0)
                if pp > 1:
                    state = lax.ppermute(state, "pp", perm)
            # per-device partial: sum over local tokens / global token count
            return total / n_tokens_global

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # Under shard_map's VMA tracking (check_vma=True) each param is
        # device-INVARIANT over every mesh axis absent from its
        # PartitionSpec; the transpose of that implicit pbroadcast is an
        # automatic psum of the cotangent over exactly those axes.  So
        # `grads` is already fully reduced — an explicit psum here would
        # double-count.  Only the loss (a per-device partial, varying over
        # dp/sp/pp) needs reassembly.
        loss = lax.psum(loss, ("dp", "sp", "pp"))
        new_params = {k: (p - lr * grads[k]).astype(p.dtype)
                      for k, p in params.items()}
        return loss, new_params

    pspecs = {k: specs[k] for k in specs}
    from jax import shard_map

    fn = shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(pspecs, P("dp", "sp"), P("dp", "sp")),
        out_specs=(P(), pspecs),
        check_vma=True,
    )
    return jax.jit(fn)


def reference_loss(cfg: GPTConfig, params, tokens, labels):
    """Single-device dense forward for correctness checks (no parallelism)."""
    H = cfg.hidden_size
    x = params["wte"][tokens] + params["wpe"][jnp.arange(tokens.shape[1])]

    def ln(x, w, b, eps=1e-5):
        mu = jnp.mean(x, -1, keepdims=True)
        var = jnp.var(x, -1, keepdims=True)
        return (x - mu) / jnp.sqrt(var + eps) * w + b

    for li in range(cfg.num_layers):
        y = ln(x, params["ln1_w"][li], params["ln1_b"][li])
        b_, s_, _ = y.shape
        qkv = y @ params["w_qkv"][li] + params["b_qkv"][li]
        # head-major (heads, 3, d) packing — must match _block's layout
        qkv = qkv.reshape(b_, s_, cfg.num_heads, 3, H // cfg.num_heads)
        qkv = qkv.transpose(3, 0, 2, 1, 4)
        q, k, v = qkv[0], qkv[1], qkv[2]
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(H // cfg.num_heads)
        mask = jnp.tril(jnp.ones((s_, s_), bool))
        logits = jnp.where(mask, logits, -1e30)
        probs = jax.nn.softmax(logits, -1)
        attn = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        attn = attn.transpose(0, 2, 1, 3).reshape(b_, s_, H)
        x = x + attn @ params["w_out"][li] + params["b_out"][li]
        y = ln(x, params["ln2_w"][li], params["ln2_b"][li])
        h = jax.nn.gelu(y @ params["w_fc1"][li] + params["b_fc1"][li],
                        approximate=True)
        x = x + h @ params["w_fc2"][li] + params["b_fc2"][li]
    x = ln(x, params["lnf_w"], params["lnf_b"])
    logits = x @ params["lm_head"]
    logp = jax.nn.log_softmax(logits, -1)
    nll = -jnp.take_along_axis(logp, labels[..., None], -1)[..., 0]
    return jnp.mean(nll)
