"""BERT / ERNIE encoder family.

Reference parity target: the dygraph BERT used by the reference's own
integration suite (`tests/unittests/dygraph_to_static/bert_dygraph_model.py`
— PretrainModelLayer: embeddings + TransformerEncoder + pooler + masked-LM
head + next-sentence head) and the ERNIE-style variant the BASELINE
configs 2/3 name (token-type + position embeddings, tied MLM decoder).

TPU-first design: pure static shapes (masked-LM positions arrive as a
fixed-size padded index tensor), bf16-friendly (all matmuls autocast via
the amp white-list), and `jit.train_step`/`fleet.build_train_step`
compatible.  Tensor-parallel variants come from swapping Linear for the
mp_layers column/row versions through `use_parallel_layers` like GPT.
"""
from __future__ import annotations

from dataclasses import dataclass

from .. import nn
from ..nn import functional as F

__all__ = ["BertConfig", "BertModel", "BertPretrainingHeads",
           "BertForPretraining", "bert_pretrain_loss_fn", "ErnieModel"]


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    initializer_range: float = 0.02
    pad_token_id: int = 0


class BertEmbeddings(nn.Layer):
    """word + position + token-type embeddings with LayerNorm (reference
    bert_dygraph_model.py embedding section / ERNIE embeddings)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        init = nn.initializer.Normal(0.0, cfg.initializer_range)
        self.word_embeddings = nn.Embedding(
            cfg.vocab_size, cfg.hidden_size, weight_attr=init)
        self.position_embeddings = nn.Embedding(
            cfg.max_position_embeddings, cfg.hidden_size, weight_attr=init)
        self.token_type_embeddings = nn.Embedding(
            cfg.type_vocab_size, cfg.hidden_size, weight_attr=init)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size)
        self.dropout = nn.Dropout(cfg.hidden_dropout)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        import paddle_tpu as paddle

        b, s = input_ids.shape
        if position_ids is None:
            position_ids = paddle.arange(s, dtype="int32").reshape([1, s])
        if token_type_ids is None:
            token_type_ids = paddle.zeros([b, s], dtype="int32")
        emb = (self.word_embeddings(input_ids) +
               self.position_embeddings(position_ids) +
               self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(emb))


class BertModel(nn.Layer):
    """Encoder trunk: embeddings -> N TransformerEncoder layers -> pooler.
    Reference: PretrainModelLayer minus the heads
    (`dygraph_to_static/bert_dygraph_model.py`)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.config = cfg
        self.embeddings = BertEmbeddings(cfg)
        enc_layer = nn.TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_heads, cfg.intermediate_size,
            dropout=cfg.hidden_dropout, activation="gelu",
            attn_dropout=cfg.attention_dropout, act_dropout=0.0,
            normalize_before=False)
        self.encoder = nn.TransformerEncoder(enc_layer, cfg.num_layers)
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                position_ids=None):
        import paddle_tpu as paddle

        if attention_mask is None:
            attention_mask = (input_ids != self.config.pad_token_id)
        # [B, S] bool -> additive [B, 1, 1, S] mask
        mask = attention_mask.astype("float32").reshape(
            [input_ids.shape[0], 1, 1, input_ids.shape[1]])
        mask = (1.0 - mask) * -1e4
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        seq = self.encoder(x, src_mask=mask)
        pooled = F.tanh(self.pooler(seq[:, 0]))
        return seq, pooled


class BertPretrainingHeads(nn.Layer):
    """Masked-LM head (tied to word embeddings) + next-sentence head
    (reference PretrainModelLayer `pooled_fc` + `mask_lm_out_bias` path)."""

    def __init__(self, cfg: BertConfig, word_embedding_weight):
        super().__init__()
        self.transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size)
        self.decoder_weight = word_embedding_weight  # tied [V, H]
        self.decoder_bias = self.create_parameter(
            [cfg.vocab_size], is_bias=True)
        self.seq_relationship = nn.Linear(cfg.hidden_size, 2)

    def forward(self, sequence_output, pooled_output, masked_positions):
        """masked_positions: [B, P] int32, static shape.  When P is padded
        (fewer than P real masks), the caller must pass matching
        `masked_weights` to `bert_pretrain_loss_fn` — the heads compute
        logits for every slot and only the loss can tell padding apart."""
        import paddle_tpu as paddle

        b, s, h = sequence_output.shape
        flat = sequence_output.reshape([b * s, h])
        offset = (paddle.arange(b, dtype="int32") * s).reshape([b, 1])
        idx = (masked_positions + offset).reshape([-1])
        picked = flat.gather(idx)  # [B*P, H]
        x = self.layer_norm(F.gelu(self.transform(picked)))
        logits = x.matmul(self.decoder_weight, transpose_y=True) + \
            self.decoder_bias
        return logits, self.seq_relationship(pooled_output)


class BertForPretraining(nn.Layer):
    """MLM + NSP pretraining model (reference PretrainModelLayer)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.bert = BertModel(cfg)
        self.heads = BertPretrainingHeads(
            cfg, self.bert.embeddings.word_embeddings.weight)

    def forward(self, input_ids, token_type_ids, masked_positions,
                attention_mask=None):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        return self.heads(seq, pooled, masked_positions)


def bert_pretrain_loss_fn(model, input_ids, token_type_ids,
                          masked_positions, masked_labels, nsp_labels,
                          masked_weights=None):
    """MLM + NSP loss (reference PretrainModelLayer.forward loss tail).
    `masked_weights` ([B, P], 1.0 = real mask slot) zeroes padded slots;
    None means every slot in masked_positions is a real mask."""
    import paddle_tpu as paddle

    mlm_logits, nsp_logits = model(input_ids, token_type_ids,
                                   masked_positions)
    mlm_loss = F.cross_entropy(mlm_logits.astype("float32"),
                               masked_labels.reshape([-1]),
                               reduction="none")
    if masked_weights is not None:
        w = masked_weights.reshape([-1]).astype("float32")
        mlm_loss = (mlm_loss * w).sum() / w.sum().clip(min=1.0)
    else:
        mlm_loss = mlm_loss.mean()
    nsp_loss = F.cross_entropy(nsp_logits.astype("float32"), nsp_labels,
                               reduction="mean")
    return mlm_loss + nsp_loss


class ErnieModel(BertModel):
    """ERNIE 1.0/3.0-style encoder (reference parity row: ERNIE-class
    models are architecturally this BERT trunk; knowledge masking is a
    data-pipeline concern, and the 10B-scale variants are this config
    scaled up and run through fleet sharding/TP)."""

    def __init__(self, vocab_size=18000, hidden_size=768, num_layers=12,
                 num_heads=12, intermediate_size=3072,
                 max_position_embeddings=513, type_vocab_size=2, **kw):
        cfg = BertConfig(
            vocab_size=vocab_size, hidden_size=hidden_size,
            num_layers=num_layers, num_heads=num_heads,
            intermediate_size=intermediate_size,
            max_position_embeddings=max_position_embeddings,
            type_vocab_size=type_vocab_size, **kw)
        super().__init__(cfg)
