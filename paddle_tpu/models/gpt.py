"""GPT/ERNIE-class decoder-only language model — the flagship model family.

Reference capability anchor: the reference trains ERNIE/GPT-scale models via
fleet hybrid parallelism (SURVEY.md §2.3, BASELINE.md configs 2-4); its
transformer building blocks live in `python/paddle/nn/layer/transformer.py`
and the TP variants in `fleet/meta_parallel/parallel_layers/mp_layers.py`.

This implementation is TPU-first: pre-LN blocks whose attention routes
through `F.scaled_dot_product_attention` (Pallas flash-attention on TPU),
bf16-friendly, with `mesh_axes` annotations on every weight so the same
module runs dense (single chip) or tensor-parallel under
`fleet.build_train_step` without code changes.  Homogeneous blocks keep the
model stackable for the pipeline schedule (paddle_tpu/parallel/pipeline.py).
"""
from __future__ import annotations

import math
from dataclasses import dataclass

from .. import nn
from ..core.tensor import Tensor
from ..distributed.fleet.meta_parallel import (ColumnParallelLinear,
                                               ParallelCrossEntropy,
                                               RowParallelLinear,
                                               VocabParallelEmbedding)
from ..nn import functional as F


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    intermediate_size: int = None
    dropout: float = 0.0
    tie_embeddings: bool = True
    use_parallel_layers: bool = True

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size

    def draft_config(self, num_layers=1, hidden_size=None, num_heads=None,
                     intermediate_size=None):
        """Shrunk config for a speculative-decoding draft model
        (`inference.speculative.DraftModelDrafter`): vocab and position
        table are pinned to the target's (the drafter must propose over
        the same token space and cover the same horizon), everything
        that buys speed shrinks.  Defaults: 1 layer, half the width
        (rounded up to keep head_dim * num_heads == hidden_size).
        """
        if hidden_size is None:
            head_dim = self.hidden_size // self.num_heads
            hidden_size = max(head_dim, self.hidden_size // 2)
        if num_heads is None:
            num_heads = max(1, min(self.num_heads,
                                   hidden_size //
                                   (self.hidden_size // self.num_heads)))
        if hidden_size % num_heads:
            raise ValueError(
                f"draft hidden_size {hidden_size} not divisible by "
                f"num_heads {num_heads}")
        return GPTConfig(
            vocab_size=self.vocab_size, hidden_size=hidden_size,
            num_layers=num_layers, num_heads=num_heads,
            max_seq_len=self.max_seq_len,
            intermediate_size=intermediate_size, dropout=0.0,
            tie_embeddings=self.tie_embeddings,
            use_parallel_layers=False)


class GPTBlock(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        h = cfg.hidden_size
        self.ln1 = nn.LayerNorm(h)
        self.ln2 = nn.LayerNorm(h)
        self.num_heads = cfg.num_heads
        self.head_dim = h // cfg.num_heads
        Col = ColumnParallelLinear if cfg.use_parallel_layers else None
        if cfg.use_parallel_layers:
            self.qkv = ColumnParallelLinear(h, 3 * h, gather_output=False)
            self.out_proj = RowParallelLinear(h, h, input_is_parallel=True)
            self.fc1 = ColumnParallelLinear(h, cfg.intermediate_size,
                                            gather_output=False)
            self.fc2 = RowParallelLinear(cfg.intermediate_size, h,
                                         input_is_parallel=True)
        else:
            self.qkv = nn.Linear(h, 3 * h)
            self.out_proj = nn.Linear(h, h)
            self.fc1 = nn.Linear(h, cfg.intermediate_size)
            self.fc2 = nn.Linear(cfg.intermediate_size, h)
        self.dropout = nn.Dropout(cfg.dropout)

    def forward(self, x, cache=None, prealloc_mask=None):
        """``cache`` enables incremental decode:

        * ``("concat", k, v)`` or ``("concat", None, None)`` — legacy
          concat-growth cache (O(S^2) reallocation over a generation;
          kept as the bit-parity baseline for the paged engine);
        * a ``PreallocKVCache`` (see ``GPT.gen_caches``) — preallocated
          buffers written in place, shape-stable per step so every
          decode step hits the eager dispatch cache.

        Returns ``x`` (no cache) or ``(x, new_cache)``.
        """
        from ..nn.layer.transformer import (PreallocKVCache,
                                            kv_cache_write, kv_valid_mask)
        from ..ops import concat, reshape, transpose

        b, s, h = x.shape
        y = self.ln1(x)
        qkv = self.qkv(y)  # [b, s, 3h] (mp-sharded on last dim under TP)
        qkv = reshape(qkv, [b, s, 3, self.num_heads, self.head_dim])
        qkv = transpose(qkv, [2, 0, 3, 1, 4])  # [3, b, H, s, d]
        q, k, v = qkv[0], qkv[1], qkv[2]
        if cache is None:
            attn = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        elif isinstance(cache, PreallocKVCache):
            # GPT.forward hoists the (identical-across-layers) validity
            # mask and the capacity check to once per step; standalone
            # block use computes/checks locally
            hoisted = prealloc_mask is not None
            full_k = kv_cache_write(cache.k, k, cache.length,
                                    check_capacity=not hoisted)
            full_v = kv_cache_write(cache.v, v, cache.length,
                                    check_capacity=False)
            mask = prealloc_mask if hoisted else \
                kv_valid_mask(cache.length, s, full_k.shape[2])
            attn = F.scaled_dot_product_attention(q, full_k, full_v,
                                                  attn_mask=mask)
            cache = PreallocKVCache(full_k, full_v, cache.length + s)
        else:
            _, k_prev, v_prev = cache
            if k_prev is not None:
                k = concat([k_prev, k], axis=2)
                v = concat([v_prev, v], axis=2)
            cache = ("concat", k, v)
            # bottom-right-aligned causal mask: an appended query row
            # attends to the whole prefix plus its own causal tail
            attn = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        attn = transpose(attn, [0, 2, 1, 3])
        attn = reshape(attn, [b, s, h])
        x = x + self.dropout(self.out_proj(attn))
        y = self.ln2(x)
        x = x + self.dropout(self.fc2(F.gelu(self.fc1(y), approximate=True)))
        return x if cache is None else (x, cache)


class GPT(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        if cfg.use_parallel_layers:
            self.wte = VocabParallelEmbedding(cfg.vocab_size, cfg.hidden_size)
        else:
            self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.wpe = nn.Embedding(cfg.max_seq_len, cfg.hidden_size)
        self.blocks = nn.LayerList([GPTBlock(cfg) for _ in range(cfg.num_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size)
        if not cfg.tie_embeddings:
            if cfg.use_parallel_layers:
                self.lm_head = ColumnParallelLinear(
                    cfg.hidden_size, cfg.vocab_size, has_bias=False,
                    gather_output=True)
            else:
                self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                         bias_attr=False)
        # scaled init for residual projections (GPT-2 convention)
        std = 0.02 / math.sqrt(2 * cfg.num_layers)
        from ..nn import initializer as init

        for blk in self.blocks:
            init.Normal(0.0, std)(blk.out_proj.weight)
            init.Normal(0.0, std)(blk.fc2.weight)

    def forward(self, input_ids, caches=None, pos_start=0):
        import jax.numpy as jnp

        from ..ops import matmul

        b, s = input_ids.shape
        pos = Tensor(jnp.arange(pos_start, pos_start + s, dtype=jnp.int32))
        x = self.wte(input_ids) + self.wpe(pos)
        new_caches = [] if caches is not None else None
        prealloc_mask = None
        if caches:
            from ..nn.layer.transformer import (PreallocKVCache,
                                                kv_capacity_check,
                                                kv_valid_mask)

            if isinstance(caches[0], PreallocKVCache):
                # every layer shares (length, s, max_length): build the
                # validity mask and run the overflow check ONCE per step
                smax = caches[0].k.shape[2]
                kv_capacity_check(caches[0].length, s, smax)
                prealloc_mask = kv_valid_mask(caches[0].length, s, smax)
        for i, blk in enumerate(self.blocks):
            if caches is None:
                x = blk(x)
            else:
                x, c = blk(x, cache=caches[i],
                           prealloc_mask=prealloc_mask)
                new_caches.append(c)
        x = self.ln_f(x)
        if self.cfg.tie_embeddings:
            logits = matmul(x, self.wte.weight, transpose_y=True)
        else:
            logits = self.lm_head(x)
        return logits if caches is None else (logits, new_caches)

    def gen_caches(self, batch_size, mode="prealloc", max_length=None,
                   dtype=None):
        """Per-block decode caches.  ``prealloc`` allocates the full
        ``max_length`` horizon once (in-place writes, shape-stable
        steps); ``concat`` is the legacy growth cache.  ``dtype``
        defaults to the model's weight dtype so bf16 models keep bf16
        K/V (kv_cache_write casts into the buffer dtype — a f32 default
        would silently upcast and break concat/prealloc parity)."""
        if mode == "concat":
            return [("concat", None, None) for _ in self.blocks]
        if mode != "prealloc":
            raise ValueError(
                f"cache mode must be 'prealloc' or 'concat', got {mode!r}")
        if max_length is None:
            max_length = self.cfg.max_seq_len
        if dtype is None:
            dtype = str(self.wte.weight.dtype)
        import jax.numpy as jnp

        from ..nn.layer.transformer import PreallocKVCache
        from ..ops import zeros

        caches = []
        for blk in self.blocks:
            k = zeros([batch_size, blk.num_heads, int(max_length),
                       blk.head_dim], dtype=dtype)
            v = zeros([batch_size, blk.num_heads, int(max_length),
                       blk.head_dim], dtype=dtype)
            caches.append(PreallocKVCache(
                k, v, Tensor(jnp.zeros((), jnp.int32))))
        return caches

    def generate(self, input_ids, max_new_tokens=32, eos_token_id=None,
                 sampler="greedy", temperature=1.0, top_k=0, top_p=1.0,
                 seed=0, use_cache="prealloc"):
        """Eager autoregressive decode (the single-model counterpart of
        `inference.serving.DecodeEngine`).  ``use_cache="concat"`` runs
        the legacy concat-growth KV cache (the slow baseline measured by
        tools/bench_decode.py); ``"prealloc"`` the in-place cache.
        Returns generated token ids [B, <=max_new_tokens] int32."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ..core import framework
        from ..nn.decode import sample_logits

        b, p_len = input_ids.shape
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if self.cfg.dropout and self.training:
            # same contract as DecodeEngine: decoding under live dropout
            # would corrupt tokens and break cache-mode parity
            raise ValueError(
                "generate() is inference only: call model.eval() first "
                "(cfg.dropout > 0 and the model is in train mode)")
        if p_len + max_new_tokens > self.cfg.max_seq_len:
            # positions past the wpe table would silently clamp in the
            # embedding gather and corrupt every later token
            raise ValueError(
                f"prompt ({p_len}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_seq_len {self.cfg.max_seq_len}")
        if use_cache == "prealloc":
            caches = self.gen_caches(b, "prealloc",
                                     max_length=p_len + max_new_tokens)
        elif use_cache == "concat":
            caches = self.gen_caches(b, "concat")
        else:
            # a typo'd mode silently hitting the slow concat baseline
            # would corrupt benchmarks — validate loudly
            raise ValueError(
                f"use_cache must be 'prealloc' or 'concat', "
                f"got {use_cache!r}")
        # inference only: without no_grad every step's ops would record
        # tape nodes whose saved inputs pin whole KV buffers per step
        with framework.no_grad_guard():
            logits, caches = self(input_ids, caches=caches, pos_start=0)
            key = jax.random.PRNGKey(seed)
            last = logits[:, -1]._array.astype(jnp.float32)
            out = []
            finished = np.zeros(b, bool)
            for step in range(max_new_tokens):
                tok = np.asarray(sample_logits(
                    last, sampler=sampler, temperature=temperature,
                    top_k=top_k, top_p=top_p,
                    key=jax.random.fold_in(key, step)))
                if eos_token_id is not None:
                    # rows that already finished keep emitting eos (the
                    # per-request semantics of the serving engine,
                    # padded)
                    tok = np.where(finished, np.int32(eos_token_id), tok)
                    finished |= tok == eos_token_id
                out.append(tok)
                if eos_token_id is not None and bool(finished.all()):
                    break
                if step == max_new_tokens - 1:
                    break
                logits, caches = self(Tensor(tok[:, None]), caches=caches,
                                      pos_start=p_len + step)
                last = logits[:, -1]._array.astype(jnp.float32)
        return Tensor(jnp.asarray(np.stack(out, axis=1)))


def gpt_loss_fn(model, input_ids, labels):
    """Next-token cross entropy (the fleet train-step loss callable)."""
    logits = model(input_ids)
    loss = F.cross_entropy(logits, labels, reduction="mean")
    return loss
