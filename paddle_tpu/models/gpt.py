"""GPT/ERNIE-class decoder-only language model — the flagship model family.

Reference capability anchor: the reference trains ERNIE/GPT-scale models via
fleet hybrid parallelism (SURVEY.md §2.3, BASELINE.md configs 2-4); its
transformer building blocks live in `python/paddle/nn/layer/transformer.py`
and the TP variants in `fleet/meta_parallel/parallel_layers/mp_layers.py`.

This implementation is TPU-first: pre-LN blocks whose attention routes
through `F.scaled_dot_product_attention` (Pallas flash-attention on TPU),
bf16-friendly, with `mesh_axes` annotations on every weight so the same
module runs dense (single chip) or tensor-parallel under
`fleet.build_train_step` without code changes.  Homogeneous blocks keep the
model stackable for the pipeline schedule (paddle_tpu/parallel/pipeline.py).
"""
from __future__ import annotations

import math
from dataclasses import dataclass

from .. import nn
from ..core.tensor import Tensor
from ..distributed.fleet.meta_parallel import (ColumnParallelLinear,
                                               ParallelCrossEntropy,
                                               RowParallelLinear,
                                               VocabParallelEmbedding)
from ..nn import functional as F


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    intermediate_size: int = None
    dropout: float = 0.0
    tie_embeddings: bool = True
    use_parallel_layers: bool = True

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size


class GPTBlock(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        h = cfg.hidden_size
        self.ln1 = nn.LayerNorm(h)
        self.ln2 = nn.LayerNorm(h)
        self.num_heads = cfg.num_heads
        self.head_dim = h // cfg.num_heads
        Col = ColumnParallelLinear if cfg.use_parallel_layers else None
        if cfg.use_parallel_layers:
            self.qkv = ColumnParallelLinear(h, 3 * h, gather_output=False)
            self.out_proj = RowParallelLinear(h, h, input_is_parallel=True)
            self.fc1 = ColumnParallelLinear(h, cfg.intermediate_size,
                                            gather_output=False)
            self.fc2 = RowParallelLinear(cfg.intermediate_size, h,
                                         input_is_parallel=True)
        else:
            self.qkv = nn.Linear(h, 3 * h)
            self.out_proj = nn.Linear(h, h)
            self.fc1 = nn.Linear(h, cfg.intermediate_size)
            self.fc2 = nn.Linear(cfg.intermediate_size, h)
        self.dropout = nn.Dropout(cfg.dropout)

    def forward(self, x):
        from ..ops import concat, reshape, transpose

        b, s, h = x.shape
        y = self.ln1(x)
        qkv = self.qkv(y)  # [b, s, 3h] (mp-sharded on last dim under TP)
        qkv = reshape(qkv, [b, s, 3, self.num_heads, self.head_dim])
        qkv = transpose(qkv, [2, 0, 3, 1, 4])  # [3, b, H, s, d]
        q, k, v = qkv[0], qkv[1], qkv[2]
        attn = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        attn = transpose(attn, [0, 2, 1, 3])
        attn = reshape(attn, [b, s, h])
        x = x + self.dropout(self.out_proj(attn))
        y = self.ln2(x)
        x = x + self.dropout(self.fc2(F.gelu(self.fc1(y), approximate=True)))
        return x


class GPT(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        if cfg.use_parallel_layers:
            self.wte = VocabParallelEmbedding(cfg.vocab_size, cfg.hidden_size)
        else:
            self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.wpe = nn.Embedding(cfg.max_seq_len, cfg.hidden_size)
        self.blocks = nn.LayerList([GPTBlock(cfg) for _ in range(cfg.num_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size)
        if not cfg.tie_embeddings:
            if cfg.use_parallel_layers:
                self.lm_head = ColumnParallelLinear(
                    cfg.hidden_size, cfg.vocab_size, has_bias=False,
                    gather_output=True)
            else:
                self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                         bias_attr=False)
        # scaled init for residual projections (GPT-2 convention)
        std = 0.02 / math.sqrt(2 * cfg.num_layers)
        from ..nn import initializer as init

        for blk in self.blocks:
            init.Normal(0.0, std)(blk.out_proj.weight)
            init.Normal(0.0, std)(blk.fc2.weight)

    def forward(self, input_ids):
        from ..ops import arange, matmul

        b, s = input_ids.shape
        pos = arange(s, dtype="int64")
        x = self.wte(input_ids) + self.wpe(pos)
        for blk in self.blocks:
            x = blk(x)
        x = self.ln_f(x)
        if self.cfg.tie_embeddings:
            logits = matmul(x, self.wte.weight, transpose_y=True)
        else:
            logits = self.lm_head(x)
        return logits


def gpt_loss_fn(model, input_ids, labels):
    """Next-token cross entropy (the fleet train-step loss callable)."""
    logits = model(input_ids)
    loss = F.cross_entropy(logits, labels, reduction="mean")
    return loss
