"""Flagship model zoo (reference: ERNIE/GPT-class language models trained
via fleet, plus the paddle.vision CNNs re-exported here)."""
from .gpt import GPT, GPTConfig, gpt_loss_fn
from ..vision.models import LeNet, ResNet, resnet50
