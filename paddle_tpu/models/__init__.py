"""Flagship model zoo (reference: ERNIE/GPT-class language models trained
via fleet, plus the paddle.vision CNNs re-exported here)."""
from .bert import (BertConfig, BertForPretraining, BertModel, ErnieModel,
                   bert_pretrain_loss_fn)
from .gpt import GPT, GPTConfig, gpt_loss_fn
from ..vision.models import (LeNet, ResNet, VisionTransformer, resnet50,
                             vit_b_16)
