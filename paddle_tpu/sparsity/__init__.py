"""ASP — automatic structured (n:m) sparsity.

Reference: `python/paddle/fluid/contrib/sparsity/` — `asp.py`
(`prune_model`, `decorate`, `set_excluded_layers`), `utils.py`
(n:m mask creation `create_mask`, `check_sparsity`).

TPU-native: 2:4 masks are computed with one top-k over reshaped groups
(no Ampere sparse-tensor-core format needed — on TPU the win is model
compression and the masked matmul staying dense on the MXU), and mask
preservation after optimizer steps is a wrapper over `step()` instead of
rewritten update ops.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..core import framework
from ..core.tensor import Tensor, unwrap
from ..nn.layer.layers import Layer

__all__ = [
    "calculate_density", "create_mask", "check_mask", "prune_model",
    "decorate", "set_excluded_layers", "reset_excluded_layers", "ASPHelper",
]

_excluded: Dict[int, set] = {}


def calculate_density(x) -> float:
    """reference `sparsity/utils.py calculate_density`."""
    a = np.asarray(unwrap(x) if isinstance(x, Tensor) else x)
    return float((a != 0).sum() / a.size)


def create_mask(x, n=2, m=4):
    """n:m mask along the last axis: keep the n largest-magnitude entries of
    every group of m (reference `utils.py create_mask`, MaskAlgo_MASK_1D).
    Tail elements (size % m) are kept dense."""
    a = np.asarray(unwrap(x) if isinstance(x, Tensor) else x)
    flat = a.reshape(-1)
    g = (flat.size // m) * m
    groups = np.abs(flat[:g]).reshape(-1, m)
    # indices of the top-n per group
    order = np.argsort(-groups, axis=1)[:, :n]
    mask = np.zeros_like(groups, dtype=np.float32)
    np.put_along_axis(mask, order, 1.0, axis=1)
    out = np.ones_like(flat, dtype=np.float32)
    out[:g] = mask.reshape(-1)
    return out.reshape(a.shape)


def check_mask(x, n=2, m=4) -> bool:
    """reference `utils.py check_sparsity`: every complete group of m has at
    most n nonzeros."""
    a = np.asarray(unwrap(x) if isinstance(x, Tensor) else x)
    flat = a.reshape(-1)
    g = (flat.size // m) * m
    if g == 0:
        return True
    nz = (flat[:g].reshape(-1, m) != 0).sum(axis=1)
    return bool((nz <= n).all())


def set_excluded_layers(param_names: List[str], model: Layer):
    _excluded[id(model)] = set(param_names)


def reset_excluded_layers(model: Layer = None):
    if model is None:
        _excluded.clear()
    else:
        _excluded.pop(id(model), None)


def _prunable(model: Layer):
    """Multi-dim weights of Linear/Conv sublayers (reference
    `asp.py _is_supported_layer`)."""
    excluded = _excluded.get(id(model), set())
    for name, p in model.named_parameters():
        if p is None or p.ndim < 2:
            continue  # biases / norm scales stay dense
        if name in excluded:
            continue
        yield name, p


class ASPHelper:
    """reference `asp.py ASPHelper`: owns the per-model masks."""

    _masks: Dict[int, Dict[str, np.ndarray]] = {}

    @classmethod
    def prune_model(cls, model: Layer, n=2, m=4, mask_algo="mask_1d",
                    with_mask=True):
        masks = {}
        with framework.no_grad_guard():
            for name, p in _prunable(model):
                mask = create_mask(p, n=n, m=m)
                p._array = p._array * jnp.asarray(mask)
                masks[name] = mask
        cls._masks[id(model)] = masks
        return masks

    @classmethod
    def reapply_masks(cls, model: Layer):
        masks = cls._masks.get(id(model))
        if not masks:
            return
        with framework.no_grad_guard():
            for name, p in model.named_parameters():
                mask = masks.get(name)
                if mask is not None:
                    p._array = p._array * jnp.asarray(mask)


def prune_model(model: Layer, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Prune supported weights to n:m sparsity in place (reference
    `asp.py prune_model`)."""
    return ASPHelper.prune_model(model, n=n, m=m, mask_algo=mask_algo,
                                 with_mask=with_mask)


class _ASPOptimizer:
    """Optimizer wrapper that re-applies masks after every step so pruned
    entries stay zero (reference `asp.py decorate` rewrites the update ops
    to multiply by the mask variables)."""

    def __init__(self, optimizer, model: Layer):
        self._inner = optimizer
        self._model = model

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def step(self):
        self._inner.step()
        ASPHelper.reapply_masks(self._model)

    def clear_grad(self, set_to_zero=False):
        self._inner.clear_grad(set_to_zero)

    clear_gradients = clear_grad


def decorate(optimizer, model: Optional[Layer] = None):
    """reference `asp.py decorate(optimizer)` — wraps the optimizer so
    masked weights remain masked through training."""
    if model is None:
        raise ValueError("paddle_tpu.sparsity.decorate requires model=")
    return _ASPOptimizer(optimizer, model)
