"""`paddle.linalg` namespace.

Reference: `python/paddle/linalg.py` re-exports the linear-algebra subset of
the tensor API (cholesky, inv, norm, ...).  All implementations live in
`paddle_tpu/ops/linalg.py` and lower to XLA linalg HLOs.
"""
from .ops.linalg import (cholesky, cholesky_solve, cond, det, dist, eig,
                         eigh, eigvalsh, inverse, lstsq, matrix_power,
                         matrix_rank, multi_dot, norm, p_norm, pinv, qr,
                         slogdet, solve, svd, triangular_solve)

inv = inverse

__all__ = [
    "cholesky", "cholesky_solve", "cond", "det", "dist", "eig", "eigh",
    "eigvalsh", "inv", "inverse", "lstsq", "matrix_power", "matrix_rank",
    "multi_dot", "norm", "p_norm", "pinv", "qr", "slogdet", "solve", "svd",
    "triangular_solve",
]
