"""ParamAttr — per-parameter creation attributes.

Reference: `python/paddle/fluid/param_attr.py` (ParamAttr, WeightNormParamAttr):
name, initializer, learning_rate multiplier, regularizer, trainable flag,
do_model_average.  Consumed by `Layer.create_parameter`
(`fluid/dygraph/layers.py`).
"""
from __future__ import annotations

__all__ = ["ParamAttr"]


class ParamAttr:
    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = float(learning_rate)
        self.regularizer = regularizer
        self.trainable = bool(trainable)
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(arg):
        """Normalize user input (None | str | bool | initializer | ParamAttr)
        to a ParamAttr, mirroring reference `ParamAttr._to_attr`."""
        if arg is None:
            return ParamAttr()
        if isinstance(arg, ParamAttr):
            return arg
        if arg is False:
            return False  # means "no parameter" (e.g. bias_attr=False)
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        # assume an initializer object
        return ParamAttr(initializer=arg)
