from . import io
