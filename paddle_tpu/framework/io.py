"""paddle.save / paddle.load.

Reference: `python/paddle/framework/io.py:222` — pickled nested state dicts
with tensor payloads.  Here tensors serialize as numpy arrays inside a
pickle; layout is a plain nested dict so checkpoints are portable and
inspectable.  Large-scale sharded checkpointing is provided separately via
orbax in `paddle_tpu.distributed.fleet.checkpoint`.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor


def _pack(obj):
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "data": obj.numpy(),
                "stop_gradient": obj.stop_gradient}
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        packed = [_pack(v) for v in obj]
        return packed if isinstance(obj, list) else tuple(packed)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            if return_numpy:
                return obj["data"]
            t = Tensor(obj["data"])
            t.stop_gradient = obj.get("stop_gradient", True)
            return t
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        out = [_unpack(v, return_numpy) for v in obj]
        return out if isinstance(obj, list) else tuple(out)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy=return_numpy)
