"""Sequence decoding: BeamSearchDecoder + dynamic_decode.

Reference: `python/paddle/nn/decode.py` (BeamSearchDecoder over RNN cells,
dynamic_decode loop) built on the `beam_search` / `beam_search_decode` /
`gather_tree` ops (`operators/beam_search_op.*`,
`operators/gather_tree_op.*`).

TPU-native: the decode loop is a fixed `max_step_num` python loop over
jit-cacheable steps (static shapes — the reference's dynamic while_op
stopping is replaced by finished-masking, the standard XLA decode idiom);
the backtrace uses the `gather_tree` op.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, unwrap
from ..ops.misc import gather_tree

__all__ = ["BeamSearchDecoder", "dynamic_decode", "sample_logits"]


def sample_logits(logits, sampler="greedy", temperature=1.0, top_k=0,
                  top_p=1.0, key=None):
    """Token sampling over vocab logits [B, V] -> [B] int32: ``greedy``
    (deterministic argmax), ``top_k``, ``top_p`` (nucleus).  Shared by
    eager `models.gpt.GPT.generate`, the serving engine
    (`inference.serving.DecodeEngine`), and the speculative-decode
    verify step (`inference.speculative`) — all decode paths draw from
    the exact same distribution, which is what makes the spec-decode
    accept/resample rule distribution-preserving by construction.
    Stochastic samplers need ``key``.

    Edge cases are pinned by tests/test_spec_decode.py:
    ``temperature <= 0`` reduces to greedy (the softmax limit);
    ``top_k >= vocab`` is a no-op filter; a ``top_p`` small enough to
    exclude every token still keeps the argmax (nucleus of one)."""
    logits = unwrap(logits)
    if sampler != "greedy" and not isinstance(temperature, jax.core.Tracer) \
            and float(temperature) <= 0.0:
        # the T -> 0 limit of temperature sampling IS argmax; dividing by
        # a tiny epsilon instead would overflow the logits and make the
        # outcome an fp accident rather than the distribution's limit
        sampler = "greedy"
    if sampler == "greedy":
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if key is None:
        raise ValueError(f"sampler {sampler!r} needs a PRNG key")
    logits = logits / jnp.maximum(jnp.float32(temperature), 1e-6)
    if sampler == "top_k":
        if int(top_k) < 1:
            raise ValueError(
                f"sampler 'top_k' needs top_k >= 1, got {top_k}")
        # clamp to the vocab: k > V would raise deep inside lax.top_k
        # (and makes top_k >= vocab an exact no-op filter)
        k = min(int(top_k), logits.shape[-1])
        kth = jax.lax.top_k(logits, k)[0][..., -1:]
        logits = jnp.where(logits >= kth, logits, -1e30)
        return jax.random.categorical(key, logits).astype(jnp.int32)
    if sampler == "top_p":
        order = jnp.argsort(-logits, axis=-1)
        sorted_l = jnp.take_along_axis(logits, order, axis=-1)
        probs = jax.nn.softmax(sorted_l, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = (cum - probs) < jnp.float32(top_p)
        # rank 0 stays in the nucleus unconditionally: top_p <= 0 (or a
        # float too small to beat cum-probs==0) must degrade to greedy,
        # never to an all-masked categorical over uniform garbage
        keep = keep.at[..., 0].set(True)
        filt = jnp.where(keep, sorted_l, -1e30)
        pick = jax.random.categorical(key, filt)
        return jnp.take_along_axis(order, pick[..., None],
                                   axis=-1)[..., 0].astype(jnp.int32)
    raise ValueError(f"unknown sampler {sampler!r}")


class BeamSearchDecoder:
    """Beam search over a step cell (reference `nn/decode.py:BeamSearchDecoder`).

    cell(inputs, states) -> (cell_out, new_states); `output_fn` maps
    cell_out to vocab logits (e.g. the projection layer); `embedding_fn`
    maps token ids to the next step's inputs.
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def initialize(self, initial_states, batch_size):
        """Tile encoder-final states across beams; beam 0 active, others
        start at -inf so the first step picks distinct continuations."""
        w = self.beam_size

        def tile(s):
            a = unwrap(s)
            if a.ndim == 0:
                # shared scalar state (e.g. a PreallocKVCache length):
                # identical across beams, nothing to tile
                return s
            # explicit target shape: -1 can't be inferred for zero-size
            # leaves (an empty concat-growth KV cache has a 0 dim)
            return Tensor(jnp.repeat(a[:, None], w, axis=1).reshape(
                (a.shape[0] * w,) + a.shape[1:]))

        states = jax.tree_util.tree_map(
            tile, initial_states,
            is_leaf=lambda v: isinstance(v, Tensor))
        tokens = jnp.full((batch_size, w), self.start_token, jnp.int32)
        log_probs = jnp.tile(
            jnp.asarray([[0.0] + [-1e9] * (w - 1)], jnp.float32),
            (batch_size, 1))
        finished = jnp.zeros((batch_size, w), jnp.bool_)
        return tokens, log_probs, finished, states

    def step(self, tokens, log_probs, finished, states, batch_size):
        w = self.beam_size
        inp = Tensor(unwrap(Tensor(tokens)).reshape(-1))
        if self.embedding_fn is not None:
            inp = self.embedding_fn(inp)
        cell_out, new_states = self.cell(inp, states)
        logits = self.output_fn(cell_out) if self.output_fn else cell_out
        v = logits.shape[-1]
        logp = jax.nn.log_softmax(unwrap(logits).astype(jnp.float32), -1)
        logp = logp.reshape(batch_size, w, v)

        # finished beams only continue with end_token at zero added cost
        fin_mask = jnp.full((v,), -1e9).at[self.end_token].set(0.0)
        logp = jnp.where(finished[..., None], fin_mask[None, None], logp)

        total = log_probs[..., None] + logp  # [B, W, V]
        flat = total.reshape(batch_size, w * v)
        top_p, top_i = jax.lax.top_k(flat, w)
        parent = top_i // v  # [B, W]
        token = (top_i % v).astype(jnp.int32)

        # reorder states by parent beam
        def reorder(s):
            if unwrap(s).ndim == 0:
                # shared scalar state (PreallocKVCache length): identical
                # across beams, identity.  ONLY 0-d leaves are skipped —
                # a mis-shaped per-beam leaf must still fail loudly below
                return s
            a = unwrap(s).reshape((batch_size, w) + unwrap(s).shape[1:])
            out = jnp.take_along_axis(
                a, parent.reshape((batch_size, w) +
                                  (1,) * (a.ndim - 2)).astype(jnp.int32),
                axis=1)
            return Tensor(out.reshape((-1,) + a.shape[2:]))

        new_states = jax.tree_util.tree_map(
            reorder, new_states, is_leaf=lambda x: isinstance(x, Tensor))
        new_finished = jnp.take_along_axis(finished, parent, axis=1) | (
            token == self.end_token)
        return token, top_p, new_finished, new_states, parent


def dynamic_decode(decoder: BeamSearchDecoder, inits=None, max_step_num=32,
                   batch_size=None, output_time_major=False, **kwargs):
    """Run the decoder to max_step_num (reference `nn/decode.py
    dynamic_decode`; fixed horizon + finished masking instead of a dynamic
    while).  Returns (sequences [B, T, W] int32 — or [T, B, W] with
    output_time_major — and final log-probs [B, W])."""
    unknown = set(kwargs) - {"is_test", "return_length", "impute_finished"}
    if unknown:
        raise TypeError(f"dynamic_decode: unsupported options {unknown}")
    if batch_size is None:
        leaf = jax.tree_util.tree_leaves(
            inits, is_leaf=lambda v: isinstance(v, Tensor))[0]
        batch_size = int(unwrap(leaf).shape[0])
    tokens, log_probs, finished, states = decoder.initialize(
        inits, batch_size)
    step_ids, parents = [], []
    for _ in range(max_step_num):
        tokens, log_probs, finished, states, parent = decoder.step(
            tokens, log_probs, finished, states, batch_size)
        step_ids.append(tokens)
        parents.append(parent)
        # early exit is a host-side convenience only: under jit tracing
        # `finished` is a Tracer (no concrete bool), so fall back to the
        # fixed max_step_num horizon — finished lanes are masked anyway
        if not isinstance(finished, jax.core.Tracer) and \
                bool(jax.device_get(finished.all())):
            break
    ids = Tensor(jnp.stack(step_ids))        # [T, B, W]
    par = Tensor(jnp.stack(parents))         # [T, B, W]
    seqs = gather_tree(ids, par)             # [T, B, W]
    if not output_time_major:
        seqs = Tensor(unwrap(seqs).transpose(1, 0, 2))  # [B, T, W]
    return seqs, Tensor(log_probs)
