"""`paddle.nn` equivalent surface (reference Appendix B of SURVEY.md)."""
from . import functional
from . import initializer
from .layer.layers import Layer, Parameter
from .layer.container import LayerDict, LayerList, ParameterList, Sequential
from .layer.common import (AlphaDropout, Bilinear, ChannelShuffle,
                           CosineSimilarity, Dropout, Dropout2D, Dropout3D,
                           Embedding, Flatten, Fold, Identity, Linear, Pad1D,
                           Pad2D, Pad3D, PairwiseDistance, PixelShuffle,
                           PixelUnshuffle, Unfold, Upsample,
                           UpsamplingBilinear2D, UpsamplingNearest2D, ZeroPad2D)
from .layer.conv import (Conv1D, Conv1DTranspose, Conv2D, Conv2DTranspose,
                         Conv3D, Conv3DTranspose)
from .layer.norm import (BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
                         GroupNorm, InstanceNorm1D, InstanceNorm2D,
                         InstanceNorm3D, LayerNorm, LocalResponseNorm,
                         SpectralNorm, SyncBatchNorm)
from .layer.activation import (CELU, ELU, GELU, GLU, Hardshrink, Hardsigmoid,
                               Hardswish, Hardtanh, LeakyReLU, LogSigmoid,
                               LogSoftmax, Maxout, Mish, PReLU, ReLU, ReLU6,
                               RReLU, SELU, Sigmoid, Silu, Softmax, Softplus,
                               Softshrink, Softsign, Swish, Tanh, Tanhshrink,
                               ThresholdedReLU)
from .layer.loss import (BCELoss, BCEWithLogitsLoss, CosineEmbeddingLoss,
                         CrossEntropyLoss, CTCLoss, HingeEmbeddingLoss,
                         HSigmoidLoss, KLDivLoss, L1Loss, MarginRankingLoss,
                         MSELoss, NLLLoss, SmoothL1Loss, TripletMarginLoss)
from .layer.pooling import (AdaptiveAvgPool1D, AdaptiveAvgPool2D,
                            AdaptiveAvgPool3D, AdaptiveMaxPool1D,
                            AdaptiveMaxPool2D, AdaptiveMaxPool3D, AvgPool1D,
                            AvgPool2D, AvgPool3D, MaxPool1D, MaxPool2D,
                            MaxPool3D)
from .layer.transformer import (MultiHeadAttention, Transformer,
                                TransformerDecoder, TransformerDecoderLayer,
                                TransformerEncoder, TransformerEncoderLayer)
from .layer.rnn import (BiRNN, GRU, GRUCell, LSTM, LSTMCell, RNN, RNNCellBase,
                        SimpleRNN, SimpleRNNCell)

from ..utils.clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue

from .decode import BeamSearchDecoder, dynamic_decode
