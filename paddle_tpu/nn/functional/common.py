"""Common functionals: linear, dropout, embedding, interpolate, etc.

Reference: `operators/matmul_v2_op.*`+`elementwise_add` (linear),
`operators/dropout_op.*`, `lookup_table_v2_op.*` (embedding),
`interpolate_v2` family, `pixel_shuffle_op`, `unfold_op`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import framework
from ...core.dispatch import WHITE, dispatch
from ...core.tensor import Tensor, unwrap


def linear(x, weight, bias=None, name=None):
    # paddle weight layout: [in_features, out_features]
    def f(a, w, *b):
        out = jnp.matmul(a, w)
        if b:
            out = out + b[0]
        return out

    if bias is not None:
        return dispatch(f, x, weight, bias, amp_policy=WHITE)
    return dispatch(f, x, weight, amp_policy=WHITE)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0:
        # downscale_in_infer (the reference's legacy default) scales by the
        # keep probability at inference instead of upscaling at train time
        if mode == "downscale_in_infer" and p > 0:
            return dispatch(lambda a: a * (1.0 - p), x)
        return x if isinstance(x, Tensor) else Tensor(x)
    key = framework.get_rng_key()

    def f(a):
        shape = list(a.shape)
        if axis is not None:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)

    return dispatch(f, x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axes = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axes, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axes = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axes, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0:
        return x
    key = framework.get_rng_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def f(a):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        a_coef = (q + alpha_p**2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return (a_coef * jnp.where(keep, a, alpha_p) + b_coef).astype(a.dtype)

    return dispatch(f, x)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    def f(w, i):
        out = jnp.take(w, i, axis=0)
        if padding_idx is not None:
            out = jnp.where((i == padding_idx)[..., None], 0.0, out)
        return out

    # note arg order: weight differentiable, index not
    def g(w):
        return f(w, unwrap(x).astype(jnp.int32))

    return dispatch(g, weight)


def one_hot(x, num_classes, name=None):
    from ...ops import one_hot as _oh

    return _oh(x, num_classes)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def f(y, *pd):
        k = y.shape[-1]
        if pd:
            return (1 - epsilon) * y + epsilon * pd[0]
        return (1 - epsilon) * y + epsilon / k

    if prior_dist is not None:
        return dispatch(f, label, prior_dist)
    return dispatch(f, label)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def f(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis)
        return num / jnp.maximum(den, eps)

    return dispatch(f, x1, x2)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    def f(a, b):
        d = a - b + epsilon
        return jnp.sum(jnp.abs(d) ** p, axis=-1, keepdims=keepdim) ** (1.0 / p)

    return dispatch(f, x, y)


def bilinear(x1, x2, weight, bias=None, name=None):
    def f(a, b, w, *bi):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bi:
            out = out + bi[0]
        return out

    if bias is not None:
        return dispatch(f, x1, x2, weight, bias)
    return dispatch(f, x1, x2, weight)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c // (r * r), r, r, h, w)
            a = a.transpose(0, 1, 4, 2, 5, 3)
            return a.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, r, r, c // (r * r))
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h * r, w * r, c // (r * r))

    return dispatch(f, x)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def f(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c, h // r, r, w // r, r)
        a = a.transpose(0, 1, 3, 5, 2, 4)
        return a.reshape(n, c * r * r, h // r, w // r)

    return dispatch(f, x)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def f(a):
        n, c, h, w = a.shape
        a = a.reshape(n, groups, c // groups, h, w)
        return a.transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)

    return dispatch(f, x)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference `operators/unfold_op.*` / math/im2col)."""
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2

    def f(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, [(0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1])])
        oh = (a.shape[2] - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (a.shape[3] - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        cols = []
        for i in range(ks[0]):
            for j in range(ks[1]):
                patch = a[
                    :,
                    :,
                    i * dl[0] : i * dl[0] + oh * st[0] : st[0],
                    j * dl[1] : j * dl[1] + ow * st[1] : st[1],
                ]
                cols.append(patch)
        out = jnp.stack(cols, axis=2)  # n, c, k*k, oh, ow
        return out.reshape(n, c * ks[0] * ks[1], oh * ow)

    return dispatch(f, x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2
    out_h, out_w = output_sizes

    def f(a):
        n, ckk, L = a.shape
        c = ckk // (ks[0] * ks[1])
        ph, pw = out_h + 2 * pd[0], out_w + 2 * pd[1]
        oh = (ph - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (pw - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        a = a.reshape(n, c, ks[0], ks[1], oh, ow)
        out = jnp.zeros((n, c, ph, pw), a.dtype)
        for i in range(ks[0]):
            for j in range(ks[1]):
                out = out.at[
                    :,
                    :,
                    i * dl[0] : i * dl[0] + oh * st[0] : st[0],
                    j * dl[1] : j * dl[1] + ow * st[1] : st[1],
                ].add(a[:, :, i, j])
        return out[:, :, pd[0] : pd[0] + out_h, pd[1] : pd[1] + out_w]

    return dispatch(f, x)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW", name=None):
    """Reference `operators/interpolate_v2_op.*` (nearest/bilinear/bicubic/
    trilinear/linear/area) via jax.image.resize."""
    a = unwrap(x)
    channel_first = data_format.startswith("NC")
    nd = a.ndim - 2
    if size is not None:
        if isinstance(size, Tensor):
            size = [int(s) for s in size.numpy().tolist()]
        out_spatial = [int(unwrap(s)) if isinstance(s, Tensor) else int(s) for s in (size if isinstance(size, (list, tuple)) else [size])]
    else:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * nd
        spatial = a.shape[2:] if channel_first else a.shape[1:-1]
        out_spatial = [int(s * f) for s, f in zip(spatial, sf)]

    method = {
        "nearest": "nearest",
        "bilinear": "bilinear",
        "bicubic": "bicubic",
        "trilinear": "trilinear",
        "linear": "linear",
        "area": "linear",
    }[mode]
    if method == "trilinear":
        method = "linear"

    def f(arr):
        if channel_first:
            out_shape = arr.shape[:2] + tuple(out_spatial)
        else:
            out_shape = (arr.shape[0],) + tuple(out_spatial) + (arr.shape[-1],)
        return jax.image.resize(arr, out_shape, method=method).astype(arr.dtype)

    return dispatch(f, x)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    def f(th):
        n, c, h, w = [int(s) for s in (out_shape if not isinstance(out_shape, Tensor) else out_shape.numpy())]
        if align_corners:
            ys = jnp.linspace(-1, 1, h)
            xs = jnp.linspace(-1, 1, w)
        else:
            ys = (jnp.arange(h) + 0.5) / h * 2 - 1
            xs = (jnp.arange(w) + 0.5) / w * 2 - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)  # h,w,3
        return jnp.einsum("nij,hwj->nhwi", th, base)

    return dispatch(f, theta)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    def f(a, g):
        n, c, h, w = a.shape
        gx, gy = g[..., 0], g[..., 1]
        if align_corners:
            ix = (gx + 1) / 2 * (w - 1)
            iy = (gy + 1) / 2 * (h - 1)
        else:
            ix = ((gx + 1) * w - 1) / 2
            iy = ((gy + 1) * h - 1) / 2
        if mode == "nearest":
            ix_r = jnp.clip(jnp.round(ix), 0, w - 1).astype(jnp.int32)
            iy_r = jnp.clip(jnp.round(iy), 0, h - 1).astype(jnp.int32)
            return a[jnp.arange(n)[:, None, None], :, iy_r, ix_r].transpose(0, 3, 1, 2)
        x0 = jnp.floor(ix)
        y0 = jnp.floor(iy)
        x1, y1 = x0 + 1, y0 + 1
        wx1, wy1 = ix - x0, iy - y0
        wx0, wy0 = 1 - wx1, 1 - wy1

        def gather(yy, xx):
            valid = (xx >= 0) & (xx <= w - 1) & (yy >= 0) & (yy <= h - 1)
            xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            v = a[jnp.arange(n)[:, None, None], :, yc, xc]  # n,hg,wg,c
            if padding_mode == "zeros":
                v = jnp.where(valid[..., None], v, 0.0)
            return v

        out = (
            gather(y0, x0) * (wy0 * wx0)[..., None]
            + gather(y0, x1) * (wy0 * wx1)[..., None]
            + gather(y1, x0) * (wy1 * wx0)[..., None]
            + gather(y1, x1) * (wy1 * wx1)[..., None]
        )
        return out.transpose(0, 3, 1, 2)

    return dispatch(f, x, grid)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    def f(a):
        nt, c, h, w = a.shape
        n = nt // seg_num
        a = a.reshape(n, seg_num, c, h, w)
        fold_c = int(c * shift_ratio)
        left = jnp.concatenate([a[:, 1:, :fold_c], jnp.zeros_like(a[:, -1:, :fold_c])], axis=1)
        right = jnp.concatenate([jnp.zeros_like(a[:, :1, fold_c:2 * fold_c]), a[:, :-1, fold_c:2 * fold_c]], axis=1)
        rest = a[:, :, 2 * fold_c:]
        out = jnp.concatenate([left, right, rest], axis=2)
        return out.reshape(nt, c, h, w)

    return dispatch(f, x)


def npair_loss(*args, **kwargs):
    from .loss import npair_loss as _n

    return _n(*args, **kwargs)
