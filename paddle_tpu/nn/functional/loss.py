"""Loss functionals.

Reference: `operators/softmax_with_cross_entropy_op.*`, `cross_entropy_op.*`,
`bce_loss_op.*`, `huber_loss_op.*`, `kldiv_loss_op.*`, `nll_loss_op.*`, etc.;
python `python/paddle/nn/functional/loss.py`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import BLACK, dispatch
from ...core.tensor import Tensor, unwrap


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, name=None):
    w = unwrap(weight) if weight is not None else None

    def f(logits, lbl):
        if soft_label or not use_softmax:
            logp = jax.nn.log_softmax(
                logits.astype(jnp.float32), axis=axis) if use_softmax \
                else jnp.log(jnp.maximum(logits.astype(jnp.float32),
                                         1e-30))
        if soft_label:
            loss = -jnp.sum(lbl * logp, axis=axis)
        else:
            li = lbl.astype(jnp.int32)
            if li.ndim == logits.ndim:
                li = jnp.squeeze(li, axis=axis)
            if use_softmax:
                # gather-then-logsumexp: never materializes the full
                # [.., V] log-prob tensor, and keeps half-precision
                # logits in their dtype with f32 ACCUMULATION only
                # (Megatron-style vocab CE) — measured ~10% faster on
                # the flagship's [16k, 50304] loss leg than upcasting
                # the logits wholesale
                m = jnp.max(logits, axis=axis, keepdims=True)
                sh = logits - m
                lse = jnp.log(jnp.sum(jnp.exp(sh.astype(jnp.float32)),
                                      axis=axis))
                # gather from the RAW logits and subtract in f32: the
                # picked logit must not be re-quantized by the bf16
                # shift (only the exp-sum terms tolerate that rounding)
                picked = (jnp.take_along_axis(
                    logits, jnp.expand_dims(li, axis), axis=axis
                ).astype(jnp.float32) - m.astype(jnp.float32)
                ).squeeze(axis)
                loss = lse - picked
            else:
                loss = -jnp.take_along_axis(
                    logp, jnp.expand_dims(li, axis), axis=axis
                ).squeeze(axis)
            mask = (li != ignore_index)
            if w is not None:
                cw = w[li]
                loss = loss * cw
                if reduction == "mean":
                    return jnp.sum(jnp.where(mask, loss, 0.0)) / jnp.maximum(
                        jnp.sum(jnp.where(mask, cw, 0.0)), 1e-12
                    )
            loss = jnp.where(mask, loss, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(mask.astype(jnp.float32)), 1.0)
        return _reduce(loss, reduction)

    if soft_label:
        return dispatch(f, input, label, amp_policy=BLACK)
    if use_softmax:
        # no BLACK upcast: the hard-label softmax path handles half
        # precision internally (f32 accumulation) — wholesale upcasting
        # the [.., V] logits under auto_cast would materialize exactly
        # the f32 tensor the kernel exists to avoid
        return dispatch(f, input, label, nondiff=(1,))
    return dispatch(f, input, label, nondiff=(1,), amp_policy=BLACK)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    from .activation import softmax as _softmax

    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def mse_loss(input, label, reduction="mean", name=None):
    return dispatch(lambda a, b: _reduce(jnp.square(a - b), reduction), input, label)


def square_error_cost(input, label):
    return dispatch(lambda a, b: jnp.square(a - b), input, label)


def l1_loss(input, label, reduction="mean", name=None):
    return dispatch(lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = a - b
        loss = jnp.where(
            jnp.abs(d) < delta, 0.5 * d * d / delta, jnp.abs(d) - 0.5 * delta
        ) * delta
        return _reduce(loss, reduction)

    return dispatch(f, input, label)


def huber_loss(input, label, delta=1.0):
    def f(a, b):
        d = a - b
        return jnp.where(jnp.abs(d) <= delta, 0.5 * d * d,
                         delta * (jnp.abs(d) - 0.5 * delta))

    return dispatch(f, input, label)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    w = unwrap(weight) if weight is not None else None

    def f(logp, lbl):
        li = lbl.astype(jnp.int32)
        loss = -jnp.take_along_axis(logp, li[..., None] if logp.ndim == li.ndim + 1
                                    else li, axis=1 if logp.ndim > 1 else 0)
        if logp.ndim == li.ndim + 1:
            loss = loss.squeeze(1)
        mask = li != ignore_index
        if w is not None:
            cw = w[li]
            loss = loss * cw
            if reduction == "mean":
                return jnp.sum(jnp.where(mask, loss, 0.0)) / jnp.maximum(
                    jnp.sum(jnp.where(mask, cw, 0.0)), 1e-12
                )
        loss = jnp.where(mask, loss, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(mask.astype(jnp.float32)), 1.0)
        return _reduce(loss, reduction)

    return dispatch(f, input, label, nondiff=(1,))


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def f(p, y, *w):
        p = jnp.clip(p, 1e-12, 1 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)

    if weight is not None:
        return dispatch(f, input, label, weight)
    return dispatch(f, input, label)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    pw = unwrap(pos_weight) if pos_weight is not None else None

    def f(z, y, *w):
        logp = jax.nn.log_sigmoid(z)
        lognp = jax.nn.log_sigmoid(-z)
        if pw is not None:
            loss = -(pw * y * logp + (1 - y) * lognp)
        else:
            loss = -(y * logp + (1 - y) * lognp)
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)

    if weight is not None:
        return dispatch(f, logit, label, weight)
    return dispatch(f, logit, label)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def f(z, y, *n):
        p = jax.nn.sigmoid(z)
        ce = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if n:
            loss = loss / n[0]
        return _reduce(loss, reduction)

    if normalizer is not None:
        return dispatch(f, logit, label, normalizer)
    return dispatch(f, logit, label)


def kl_div(input, label, reduction="mean", name=None):
    def f(logp, y):
        loss = y * (jnp.log(jnp.maximum(y, 1e-30)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)

    return dispatch(f, input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    def f(a, b, y):
        return _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction)

    return dispatch(f, input, other, label)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def f(a, y):
        loss = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)

    return dispatch(f, input, label)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def f(a, b, y):
        cos = jnp.sum(a * b, -1) / (
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1) + 1e-12
        )
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return dispatch(f, input1, input2, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-06, swap=False, reduction="mean", name=None):
    def f(a, pos, neg):
        dp = jnp.sum(jnp.abs(a - pos) ** p, -1) ** (1 / p)
        dn = jnp.sum(jnp.abs(a - neg) ** p, -1) ** (1 / p)
        if swap:
            dn2 = jnp.sum(jnp.abs(pos - neg) ** p, -1) ** (1 / p)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)

    return dispatch(f, input, positive, negative)


def log_loss(input, label, epsilon=0.0001, name=None):
    def f(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)

    return dispatch(f, input, label)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def f(a, p, l):
        sim = a @ p.T
        batch = l.reshape(-1, 1)
        target = (batch == batch.T).astype(jnp.float32)
        target = target / jnp.sum(target, axis=1, keepdims=True)
        ce = jnp.mean(
            -jnp.sum(target * jax.nn.log_softmax(sim, axis=1), axis=1)
        )
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, 1)) + jnp.mean(jnp.sum(p * p, 1))) * 0.25
        return ce + reg

    return dispatch(f, anchor, positive, labels, nondiff=(2,))


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC loss (reference `operators/warpctc_op.*`), pure-XLA forward-alpha
    recursion over `lax.scan`."""
    def f(lp, lbl):
        # lp: [T, B, C] log-softmaxed; lbl: [B, S]
        lp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        T, B, C = lp.shape
        S = lbl.shape[1]
        # extended labels: blank,l1,blank,l2,...,blank -> length 2S+1
        ext = jnp.full((B, 2 * S + 1), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(lbl.astype(jnp.int32))
        ext_len = 2 * label_lengths_arr + 1

        neg_inf = -1e30
        alpha0 = jnp.full((B, 2 * S + 1), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, jnp.arange(B), blank])
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(S > 0, lp[0, jnp.arange(B), ext[:, 1]], neg_inf)
        )

        same = jnp.concatenate(
            [jnp.ones((B, 2), dtype=bool), ext[:, 2:] == ext[:, :-2]], axis=1
        )

        in_len = input_lengths_arr  # [B]

        def step(carry, xs):
            alpha = carry
            lp_t, t = xs
            a0 = alpha
            a1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
            a2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
            a2 = jnp.where(same, neg_inf, a2)
            m = jnp.maximum(jnp.maximum(a0, a1), a2)
            new = m + jnp.log(
                jnp.exp(a0 - m) + jnp.exp(a1 - m) + jnp.exp(a2 - m) + 1e-30
            )
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            new = new + emit
            # freeze alpha for sequences already past their input length
            active = (t < in_len)[:, None]
            return jnp.where(active, new, alpha), None

        ts = jnp.arange(1, T)
        alphaT, _ = jax.lax.scan(step, alpha0, (lp[1:], ts))
        # gather at positions ext_len-1 and ext_len-2
        idx1 = jnp.clip(ext_len - 1, 0, 2 * S)
        idx2 = jnp.clip(ext_len - 2, 0, 2 * S)
        b = jnp.arange(B)
        ll = jnp.logaddexp(alphaT[b, idx1], alphaT[b, idx2])
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(label_lengths_arr, 1))
        return _reduce(loss, reduction)

    label_lengths_arr = unwrap(label_lengths).astype(jnp.int32)
    input_lengths_arr = unwrap(input_lengths).astype(jnp.int32)
    return dispatch(f, log_probs, labels, nondiff=(1,))
