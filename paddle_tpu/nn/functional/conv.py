"""Convolution functionals.

Reference: `operators/conv_op.*` + cudnn kernels (`conv_cudnn_op.cu`) and
`operators/conv_transpose_op.*`.  TPU-native: `lax.conv_general_dilated`,
which XLA tiles onto the MXU; AMP white-listed (bf16).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ...core.dispatch import WHITE, dispatch
from ...core.tensor import unwrap


def _pair(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(i) for i in v)
    return (int(v),) * n


def _padding(padding, nd):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * nd
    padding = list(padding)
    if len(padding) == nd and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * nd:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(nd)]
    return [tuple(p) for p in padding]


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, nd, data_format):
    stride = _pair(stride, nd)
    dilation = _pair(dilation, nd)
    pad = _padding(padding, nd)
    if data_format in ("NCHW", "NCL", "NCDHW"):
        dn_in = "NC" + "DHW"[3 - nd:]
    else:
        dn_in = "N" + "DHW"[3 - nd:] + "C"
    spatial = "DHW"[3 - nd:]
    dn = lax.conv_dimension_numbers(
        unwrap(x).shape,
        unwrap(weight).shape,
        (dn_in, "OI" + spatial, dn_in),
    )

    def f(a, w, *b):
        # No preferred_element_type here: the TPU MXU accumulates in f32
        # natively for bf16 operands, and the annotation breaks jax's conv
        # transpose rule under AMP (bf16 operand x f32 cotangent mismatch).
        out = lax.conv_general_dilated(
            a,
            w,
            window_strides=stride,
            padding=pad,
            rhs_dilation=dilation,
            dimension_numbers=dn,
            feature_group_count=groups,
        )
        if b:
            bias_shape = [1] * out.ndim
            c_axis = 1 if dn_in.startswith("NC") else out.ndim - 1
            bias_shape[c_axis] = b[0].shape[0]
            out = out + b[0].reshape(bias_shape)
        return out

    if bias is not None:
        return dispatch(f, x, weight, bias, amp_policy=WHITE)
    return dispatch(f, x, weight, amp_policy=WHITE)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 1, data_format)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                       dilation, groups, nd, data_format):
    stride = _pair(stride, nd)
    dilation = _pair(dilation, nd)
    out_pad = _pair(output_padding, nd)
    pad = _padding(padding, nd)
    if isinstance(pad, str):
        raise ValueError("string padding not supported for conv_transpose")
    dn_in = "NC" + "DHW"[3 - nd:] if data_format.startswith("NC") else "N" + "DHW"[3 - nd:] + "C"
    spatial = "DHW"[3 - nd:]
    # weight layout in paddle conv_transpose: [in, out//groups, *k] -> "IO"
    dn = lax.conv_dimension_numbers(
        unwrap(x).shape,
        unwrap(weight).shape,
        (dn_in, "IO" + spatial, dn_in),
    )

    def f(a, w, *b):
        # gradient-of-conv formulation: lhs_dilation=stride
        k = [(w.shape[2 + i] - 1) * dilation[i] for i in range(nd)]
        tpad = [
            (k[i] - pad[i][0], k[i] - pad[i][1] + out_pad[i]) for i in range(nd)
        ]
        out = lax.conv_general_dilated(
            a,
            jnp.flip(w, axis=tuple(range(2, 2 + nd))),
            window_strides=(1,) * nd,
            padding=tpad,
            lhs_dilation=stride,
            rhs_dilation=dilation,
            dimension_numbers=dn,
            feature_group_count=1 if groups == 1 else groups,
        )
        if b:
            bias_shape = [1] * out.ndim
            c_axis = 1 if dn_in.startswith("NC") else out.ndim - 1
            bias_shape[c_axis] = b[0].shape[0]
            out = out + b[0].reshape(bias_shape)
        return out

    if groups != 1:
        # grouped transpose: split and concat (rare path)
        from ...ops import concat as cat
        from ...ops import split as sp

        xs = sp(x, groups, axis=1 if data_format.startswith("NC") else -1)
        ws = sp(weight, groups, axis=0)
        outs = [
            _conv_transpose_nd(xi, wi, None, stride, padding, output_padding,
                               dilation, 1, nd, data_format)
            for xi, wi in zip(xs, ws)
        ]
        out = cat(outs, axis=1 if data_format.startswith("NC") else -1)
        if bias is not None:
            from ...ops import reshape

            bshape = [1] * out.ndim
            c_axis = 1 if data_format.startswith("NC") else out.ndim - 1
            bshape[c_axis] = bias.shape[0]
            out = out + reshape(bias, bshape)
        return out

    if bias is not None:
        return dispatch(f, x, weight, bias, amp_policy=WHITE)
    return dispatch(f, x, weight, amp_policy=WHITE)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, data_format="NCL", name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 1, data_format)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, data_format="NCHW", output_size=None, name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 2, data_format)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, data_format="NCDHW", output_size=None, name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 3, data_format)
