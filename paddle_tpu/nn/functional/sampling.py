"""Sampled / tree-structured classification losses.

Reference: `operators/nce_op.h` (noise-contrastive estimation with
uniform / log-uniform / custom samplers) and
`operators/hierarchical_sigmoid_op.h` + `math/matrix_bit_code.h`
(complete-binary-tree sigmoid softmax, SimpleCode bit paths).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...core import framework
from ...core.dispatch import dispatch
from ...core.tensor import Tensor, unwrap

__all__ = ["nce", "hsigmoid_loss", "hierarchical_sigmoid"]


def _log_uniform_probs(n):
    # math::LogUniformSampler: P(k) = (log(k+2) - log(k+1)) / log(range+1)
    k = np.arange(n, dtype=np.float64)
    return ((np.log(k + 2) - np.log(k + 1)) / np.log(n + 1)).astype(
        np.float32)


def nce(input, label, weight, bias=None, num_total_classes=None,
        num_neg_samples=10, sampler="uniform", custom_dist=None,
        sample_weight=None, seed=0, name=None):
    """Noise-contrastive estimation loss, exact `nce_op.h` math: per
    sampled class, o = sigmoid(x.w_c + b_c), q = P(c) * num_neg_samples;
    cost = -log(o/(o+q)) for true classes, -log(q/(o+q)) for noise.
    Negatives are drawn on host per call (like the reference's CPU
    sampler) so shapes stay static."""
    n_classes = int(num_total_classes if num_total_classes is not None
                    else unwrap(weight).shape[0])
    lab = np.asarray(jax.device_get(unwrap(label))).reshape(
        unwrap(input).shape[0], -1)
    num_true = lab.shape[1]
    k = int(num_neg_samples)
    rng = np.random.RandomState(seed or None)
    if sampler == "uniform":
        probs = np.full(n_classes, 1.0 / n_classes, np.float32)
        negs = rng.randint(0, n_classes, size=(lab.shape[0], k))
    elif sampler == "log_uniform":
        probs = _log_uniform_probs(n_classes)
        negs = rng.choice(n_classes, size=(lab.shape[0], k),
                          p=probs / probs.sum())
    elif sampler == "custom_dist":
        probs = np.asarray(custom_dist, np.float32)
        negs = rng.choice(n_classes, size=(lab.shape[0], k),
                          p=probs / probs.sum())
    else:
        raise ValueError(f"unknown sampler {sampler!r}")
    samples = np.concatenate([lab, negs], axis=1)  # [B, T+k]
    q = jnp.asarray(probs[samples] * k)            # [B, T+k]
    samples_j = jnp.asarray(samples)

    def f(x, w, *rest):
        logits = jnp.einsum("bd,btd->bt", x, w[samples_j])
        if rest:
            logits = logits + rest[0][samples_j]
        o = jax.nn.sigmoid(logits)
        cost_true = -jnp.log(o / (o + q))
        cost_noise = -jnp.log(q / (o + q))
        is_true = jnp.arange(samples.shape[1])[None, :] < num_true
        cost = jnp.where(is_true, cost_true, cost_noise).sum(axis=1)
        return cost[:, None]

    args = (input, weight) + ((bias,) if bias is not None else ())
    return dispatch(f, *args)


def _simple_code_table(num_classes):
    """SimpleCode paths for every class (matrix_bit_code.h): c = label +
    num_classes; step j uses weight row (c >> (j+1)) - 1 and bit
    (c >> j) & 1; path length = floor(log2(c)).  Returns int32
    [num_classes, L] node indices, [.., L] bits, [..] lengths."""
    max_len = int(np.floor(np.log2(2 * num_classes - 1)))
    idx = np.zeros((num_classes, max_len), np.int32)
    bits = np.zeros((num_classes, max_len), np.float32)
    lens = np.zeros(num_classes, np.int32)
    for c in range(num_classes):
        code = c + num_classes
        L = int(np.floor(np.log2(code)))
        lens[c] = L
        for j in range(L):
            idx[c, j] = (code >> (j + 1)) - 1
            bits[c, j] = (code >> j) & 1
    return idx, bits, lens


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss, exact `hierarchical_sigmoid_op.h`
    semantics: z_j = clip(x.w[idx_j] + b[idx_j], ±40); loss = Σ_j
    softplus(z_j over the FULL code_length, out-of-path z=0) - Σ_{j<L}
    bit_j z_j.  Default tree = complete binary over num_classes
    (SimpleCode); custom trees via path_table/path_code."""
    if path_table is not None:
        pt = np.asarray(jax.device_get(unwrap(path_table)), np.int64)
        pc = np.asarray(jax.device_get(unwrap(path_code)), np.float32)
        lab = np.asarray(jax.device_get(unwrap(label))).reshape(-1)
        idx_all = pt[lab].astype(np.int32)
        bits_all = pc[lab].astype(np.float32)
        lens_all = (idx_all >= 0).sum(axis=1).astype(np.int32)
        idx_all = np.maximum(idx_all, 0)
        code_length = idx_all.shape[1]
    else:
        idx, bits, lens = _simple_code_table(int(num_classes))
        lab = np.asarray(jax.device_get(unwrap(label))).reshape(-1)
        idx_all = idx[lab]
        bits_all = bits[lab]
        lens_all = lens[lab]
        code_length = int(np.floor(np.log2(num_classes - 1))) + 1 \
            if num_classes > 1 else 1
        idx_all = idx_all[:, :code_length]
        bits_all = bits_all[:, :code_length]
    idx_j = jnp.asarray(idx_all)
    bits_j = jnp.asarray(bits_all)
    valid = jnp.asarray(
        (np.arange(idx_all.shape[1])[None, :] < lens_all[:, None])
        .astype(np.float32))

    def f(x, w, *rest):
        z = jnp.einsum("bd,bjd->bj", x, w[idx_j])
        if rest:
            b = rest[0].reshape(-1)
            z = z + b[idx_j]
        z = jnp.clip(z * valid, -40.0, 40.0)  # out-of-path -> 0
        # softplus over the full code_length (incl. z=0 padding, matching
        # the reference's zero-initialized pre_out)
        loss = jnp.log1p(jnp.exp(z)).sum(axis=1) \
            - (bits_j * z * valid).sum(axis=1)
        return loss[:, None]

    args = (input, weight) + ((bias,) if bias is not None else ())
    return dispatch(f, *args)


hierarchical_sigmoid = hsigmoid_loss
