"""`paddle.nn.functional` equivalent surface."""
from ...ops.manipulation import pad  # noqa: F401
from ...ops.creation import one_hot as _one_hot_op  # noqa: F401

from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .attention import scaled_dot_product_attention, flash_attention  # noqa: F401
from .sampling import *  # noqa: F401,F403
