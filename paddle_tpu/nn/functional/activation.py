"""Activation functionals.

Reference: `operators/activation_op.cc` (incl. the REGISTER_ACTIVATION macro
family, `activation_op.cc:712+`) and `python/paddle/nn/functional/activation.py`.
All map to VPU elementwise HLO; XLA fuses them into adjacent matmuls/convs
(replacing the reference's `fused_*_activation` ops).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import BLACK, dispatch
from ...core.tensor import Tensor, unwrap


def relu(x, name=None):
    return dispatch(jax.nn.relu, x)


def relu6(x, name=None):
    return dispatch(lambda a: jnp.clip(a, 0, 6), x)


def relu_(x):
    out = relu(x)
    x.set_value(out._array)
    return x


def sigmoid(x, name=None):
    return dispatch(jax.nn.sigmoid, x)


def log_sigmoid(x, name=None):
    return dispatch(jax.nn.log_sigmoid, x)


def tanh(x, name=None):
    return dispatch(jnp.tanh, x)


def tanhshrink(x, name=None):
    return dispatch(lambda a: a - jnp.tanh(a), x)


def gelu(x, approximate=False, name=None):
    return dispatch(lambda a: jax.nn.gelu(a, approximate=approximate), x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return dispatch(lambda a: jax.nn.leaky_relu(a, negative_slope), x)


def elu(x, alpha=1.0, name=None):
    return dispatch(lambda a: jax.nn.elu(a, alpha), x)


def celu(x, alpha=1.0, name=None):
    return dispatch(lambda a: jax.nn.celu(a, alpha), x)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return dispatch(lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), x)


def silu(x, name=None):
    return dispatch(jax.nn.silu, x)


def swish(x, name=None):
    return dispatch(jax.nn.silu, x)


def mish(x, name=None):
    return dispatch(lambda a: a * jnp.tanh(jax.nn.softplus(a)), x)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return dispatch(lambda a: jnp.clip(a, min, max), x)


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return dispatch(lambda a: jnp.clip(a * slope + offset, 0.0, 1.0), x)


def hardswish(x, name=None):
    return dispatch(lambda a: a * jnp.clip(a + 3, 0, 6) / 6, x)


def hardshrink(x, threshold=0.5, name=None):
    return dispatch(lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), x)


def softshrink(x, threshold=0.5, name=None):
    return dispatch(
        lambda a: jnp.where(a > threshold, a - threshold,
                            jnp.where(a < -threshold, a + threshold, 0.0)),
        x,
    )


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return dispatch(
        lambda a: jnp.where(a * beta > threshold, a, jax.nn.softplus(a * beta) / beta), x
    )


def softsign(x, name=None):
    return dispatch(jax.nn.soft_sign, x)


def thresholded_relu(x, threshold=1.0, name=None):
    return dispatch(lambda a: jnp.where(a > threshold, a, 0.0), x)


def softmax(x, axis=-1, dtype=None, name=None):
    return dispatch(lambda a: jax.nn.softmax(a, axis=axis), x, amp_policy=BLACK)


def softmax_(x, axis=-1):
    out = softmax(x, axis)
    x.set_value(out._array)
    return x


def log_softmax(x, axis=-1, dtype=None, name=None):
    return dispatch(lambda a: jax.nn.log_softmax(a, axis=axis), x, amp_policy=BLACK)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core import framework

    key = framework.get_rng_key()

    def f(a):
        g = -jnp.log(-jnp.log(jax.random.uniform(key, a.shape) + 1e-20) + 1e-20)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False)
            y = jax.lax.stop_gradient(y_hard - y) + y
        return y

    return dispatch(f, x)


def maxout(x, groups, axis=1, name=None):
    def f(a):
        shape = list(a.shape)
        c = shape[axis]
        shape[axis] = c // groups
        shape.insert(axis + 1, groups)
        return jnp.max(a.reshape(shape), axis=axis + 1)

    return dispatch(f, x)


def prelu(x, weight, data_format="NCHW", name=None):
    def f(a, w):
        if w.size == 1:
            return jnp.where(a > 0, a, w.reshape(()) * a)
        shape = [1] * a.ndim
        ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
        shape[ch_axis] = w.size
        return jnp.where(a > 0, a, w.reshape(shape) * a)

    return dispatch(f, x, weight)


def glu(x, axis=-1, name=None):
    return dispatch(lambda a: jax.nn.glu(a, axis=axis), x)


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=False, name=None):
    from ...core import framework

    if not training:
        slope = (lower + upper) / 2
        return dispatch(lambda a: jnp.where(a >= 0, a, a * slope), x)
    key = framework.get_rng_key()

    def f(a):
        slopes = jax.random.uniform(key, a.shape, a.dtype, lower, upper)
        return jnp.where(a >= 0, a, a * slopes)

    return dispatch(f, x)
