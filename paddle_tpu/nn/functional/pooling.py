"""Pooling functionals via `lax.reduce_window`.

Reference: `operators/pool_op.*`, `operators/math/pooling.cu`.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from ...core.dispatch import dispatch
from ...core.tensor import unwrap


def _tup(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(i) for i in v)
    return (int(v),) * n


def _pool_nd(x, kernel, stride, padding, nd, mode, ceil_mode=False,
             exclusive=True, data_format="NCHW"):
    kernel = _tup(kernel, nd)
    stride = _tup(stride if stride is not None else kernel, nd)
    if isinstance(padding, str):
        pad_spatial = padding.upper()
    else:
        p = _tup(padding, nd) if not isinstance(padding, (list, tuple)) or all(
            isinstance(i, int) for i in padding
        ) else padding
        if isinstance(p, tuple) and len(p) == nd:
            pad_spatial = [(i, i) for i in p]
        else:
            pad_spatial = [tuple(i) for i in p]

    channel_first = data_format.startswith("NC")

    def f(a):
        if channel_first:
            window = (1, 1) + kernel
            strides = (1, 1) + stride
        else:
            window = (1,) + kernel + (1,)
            strides = (1,) + stride + (1,)
        if isinstance(pad_spatial, str):
            pads = pad_spatial
        else:
            spatial = list(pad_spatial)
            if ceil_mode:
                # ceil output size: extend the right padding so
                # reduce_window emits ceil((in+2p-k)/s)+1 windows
                # (reference pool ceil_mode semantics)
                sp_start = 2 if channel_first else 1
                for d in range(nd):
                    i = a.shape[sp_start + d]
                    lo, hi = spatial[d]
                    num = i + lo + hi - kernel[d]
                    ceil_out = -(-num // stride[d]) + 1
                    need = (ceil_out - 1) * stride[d] + kernel[d] \
                        - (i + lo + hi)
                    if need > 0:
                        spatial[d] = (lo, hi + need)
            if channel_first:
                pads = [(0, 0), (0, 0)] + spatial
            else:
                pads = [(0, 0)] + spatial + [(0, 0)]
        if mode == "max":
            init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else jnp.iinfo(a.dtype).min
            return lax.reduce_window(a, init, lax.max, window, strides, pads)
        # avg
        summed = lax.reduce_window(a.astype(jnp.float32), 0.0, lax.add, window, strides, pads)
        if exclusive and not isinstance(pads, str):
            ones = jnp.ones_like(a, dtype=jnp.float32)
            counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
            return (summed / counts).astype(a.dtype)
        return (summed / float(np.prod(kernel))).astype(a.dtype)

    return dispatch(f, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    return _pool_nd(x, kernel_size, stride, padding, 1, "max", ceil_mode,
                    data_format="NCL")


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    if return_mask:
        return _max_pool_nd_with_index(x, kernel_size, stride, padding,
                                       ceil_mode, 2,
                                       data_format == "NHWC")
    return _pool_nd(x, kernel_size, stride, padding, 2, "max", ceil_mode,
                    data_format=data_format)


def _max_pool_nd_with_index(x, kernel_size, stride, padding, ceil_mode,
                            nd, channel_last):
    """reference `max_pool{2,3}d_with_index`
    (`operators/pool_with_index_op.*`): max pool + each window's argmax
    position flattened into the input's spatial volume (what
    max_unpool consumes).  One implementation parameterized over nd."""
    ks = _tup(kernel_size, nd)
    st = _tup(stride or kernel_size, nd)
    pd = _tup(padding, nd)

    def f(a):
        if channel_last:
            a = jnp.moveaxis(a, -1, 1)
        n, c = a.shape[:2]
        spatial = a.shape[2:]

        def osize(i_, k_, p_, s_):
            num = i_ + 2 * p_ - k_
            return (num + s_ - 1) // s_ + 1 if ceil_mode else \
                num // s_ + 1

        osz = [osize(i_, k_, p_, s_)
               for i_, k_, p_, s_ in zip(spatial, ks, pd, st)]
        # ceil_mode may read past the padded edge: extend with the
        # dtype's minimum (ints use iinfo — round-4 fix)
        extra = [max((o - 1) * s_ + k_ - (i_ + 2 * p_), 0)
                 for o, s_, k_, i_, p_ in zip(osz, st, ks, spatial, pd)]
        neg = (jnp.iinfo(a.dtype).min
               if jnp.issubdtype(a.dtype, jnp.integer)
               else jnp.finfo(a.dtype).min)
        ap = jnp.pad(a, [(0, 0), (0, 0)] +
                     [(p_, p_ + e) for p_, e in zip(pd, extra)],
                     constant_values=neg)
        # window index grids per spatial dim: [O_d, K_d]
        grids = [jnp.arange(o)[:, None] * s_ + jnp.arange(k_)[None, :]
                 for o, s_, k_ in zip(osz, st, ks)]
        # gather windows -> [N, C, *O, *K] via an outer advanced index
        idx = []
        for d in range(nd):
            shape = [1] * (2 * nd)
            shape[d] = osz[d]
            shape[nd + d] = ks[d]
            idx.append(grids[d].reshape(shape))
        win = ap[(slice(None), slice(None)) + tuple(idx)]
        flat = win.reshape((n, c) + tuple(osz) + (-1,))
        arg = jnp.argmax(flat, axis=-1)
        out = jnp.take_along_axis(flat, arg[..., None], axis=-1)[..., 0]
        # decode the window-flat argmax back to global spatial coords,
        # then flatten into the input volume (row-major over spatial)
        pos = arg
        kcoord = []
        for k_ in reversed(ks):
            kcoord.append(pos % k_)
            pos = pos // k_
        kcoord = list(reversed(kcoord))
        gidx = jnp.zeros_like(arg)
        for d in range(nd):
            oshape = [1] * arg.ndim
            oshape[2 + d] = osz[d]
            base = jnp.arange(osz[d]).reshape(oshape) * st[d]
            coord = base + kcoord[d] - pd[d]
            gidx = gidx * spatial[d] + coord
        gidx = gidx.astype(jnp.int64)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
            gidx = jnp.moveaxis(gidx, 1, -1)
        return out, gidx

    return dispatch(f, x)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    if return_mask:
        return _max_pool_nd_with_index(x, kernel_size, stride, padding,
                                       ceil_mode, 3,
                                       data_format == "NDHWC")
    return _pool_nd(x, kernel_size, stride, padding, 3, "max", ceil_mode,
                    data_format=data_format)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool_nd(x, kernel_size, stride, padding, 1, "avg",
                    ceil_mode, exclusive, data_format="NCL")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 2, "avg",
                    ceil_mode, exclusive, data_format=data_format)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 3, "avg",
                    ceil_mode, exclusive, data_format=data_format)


def _adaptive_pool_nd(x, output_size, nd, mode, data_format):
    channel_first = data_format.startswith("NC")
    out_sizes = _tup(output_size, nd)

    def f(a):
        spatial_start = 2 if channel_first else 1
        out = a
        # adaptive pooling = for each spatial dim, segment into output bins
        for d in range(nd):
            axis = spatial_start + d
            in_size = out.shape[axis]
            o = out_sizes[d] if out_sizes[d] is not None else in_size
            if in_size % o == 0:
                k = in_size // o
                shape = list(out.shape)
                shape[axis] = o
                shape.insert(axis + 1, k)
                r = out.reshape(shape)
                out = jnp.max(r, axis=axis + 1) if mode == "max" else jnp.mean(r, axis=axis + 1)
            else:
                # general bins via cumulative windows
                starts = (np.arange(o) * in_size) // o
                ends = ((np.arange(o) + 1) * in_size + o - 1) // o
                segs = []
                for s, e in zip(starts, ends):
                    sl = [slice(None)] * out.ndim
                    sl[axis] = slice(int(s), int(e))
                    block = out[tuple(sl)]
                    r = jnp.max(block, axis=axis, keepdims=True) if mode == "max" else jnp.mean(
                        block, axis=axis, keepdims=True
                    )
                    segs.append(r)
                out = jnp.concatenate(segs, axis=axis)
        return out

    return dispatch(f, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool_nd(x, output_size, 1, "avg", "NCL")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool_nd(x, output_size, 2, "avg", data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool_nd(x, output_size, 3, "avg", data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool_nd(x, output_size, 1, "max", "NCL")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool_nd(x, output_size, 2, "max", "NCHW")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool_nd(x, output_size, 3, "max", "NCDHW")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCHW", name=None):
    """Inverse of max_pool2d with indices (`operators/unpool_op.*`):
    scatters each pooled value back to its argmax position."""
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size, kernel_size)
    stride = stride or kernel_size
    if isinstance(stride, int):
        stride = (stride, stride)

    def f(xv, idx):
        n, c, h, w = xv.shape
        if output_size is not None:
            oh, ow = output_size[-2:]
        else:
            oh = (h - 1) * stride[0] + kernel_size[0] - 2 * (
                padding if isinstance(padding, int) else padding[0])
            ow = (w - 1) * stride[1] + kernel_size[1] - 2 * (
                padding if isinstance(padding, int) else padding[1])
        flat = jnp.zeros((n, c, oh * ow), xv.dtype)
        nidx = jnp.arange(n)[:, None, None]
        cidx = jnp.arange(c)[None, :, None]
        flat = flat.at[nidx, cidx, idx.reshape(n, c, -1)].set(
            xv.reshape(n, c, -1))
        return flat.reshape(n, c, oh, ow)

    return dispatch(f, x, indices, nondiff=(1,))


def spatial_pyramid_pool(x, pyramid_height, pool_type="max", name=None):
    """SPP (`operators/spp_op.*`): concat adaptive {max,avg} pools at
    1x1, 2x2, ... 2^(H-1) x 2^(H-1) bins, flattened per level."""
    outs = []
    for level in range(int(pyramid_height)):
        bins = 2 ** level
        if pool_type == "max":
            p = adaptive_max_pool2d(x, bins)
        else:
            p = adaptive_avg_pool2d(x, bins)
        outs.append(p.reshape([p.shape[0], -1]))
    from ...ops import concat as _concat

    return _concat(outs, axis=1)


spp = spatial_pyramid_pool
