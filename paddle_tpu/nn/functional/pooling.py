"""Pooling functionals via `lax.reduce_window`.

Reference: `operators/pool_op.*`, `operators/math/pooling.cu`.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from ...core.dispatch import dispatch
from ...core.tensor import unwrap


def _tup(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(i) for i in v)
    return (int(v),) * n


def _pool_nd(x, kernel, stride, padding, nd, mode, ceil_mode=False,
             exclusive=True, data_format="NCHW"):
    kernel = _tup(kernel, nd)
    stride = _tup(stride if stride is not None else kernel, nd)
    if isinstance(padding, str):
        pad_spatial = padding.upper()
    else:
        p = _tup(padding, nd) if not isinstance(padding, (list, tuple)) or all(
            isinstance(i, int) for i in padding
        ) else padding
        if isinstance(p, tuple) and len(p) == nd:
            pad_spatial = [(i, i) for i in p]
        else:
            pad_spatial = [tuple(i) for i in p]

    channel_first = data_format.startswith("NC")

    def f(a):
        if channel_first:
            window = (1, 1) + kernel
            strides = (1, 1) + stride
        else:
            window = (1,) + kernel + (1,)
            strides = (1,) + stride + (1,)
        if isinstance(pad_spatial, str):
            pads = pad_spatial
        else:
            if channel_first:
                pads = [(0, 0), (0, 0)] + list(pad_spatial)
            else:
                pads = [(0, 0)] + list(pad_spatial) + [(0, 0)]
        if mode == "max":
            init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else jnp.iinfo(a.dtype).min
            return lax.reduce_window(a, init, lax.max, window, strides, pads)
        # avg
        summed = lax.reduce_window(a.astype(jnp.float32), 0.0, lax.add, window, strides, pads)
        if exclusive and not isinstance(pads, str):
            ones = jnp.ones_like(a, dtype=jnp.float32)
            counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
            return (summed / counts).astype(a.dtype)
        return (summed / float(np.prod(kernel))).astype(a.dtype)

    return dispatch(f, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    return _pool_nd(x, kernel_size, stride, padding, 1, "max", ceil_mode,
                    data_format="NCL")


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 2, "max", ceil_mode,
                    data_format=data_format)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 3, "max", ceil_mode,
                    data_format=data_format)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool_nd(x, kernel_size, stride, padding, 1, "avg",
                    ceil_mode, exclusive, data_format="NCL")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 2, "avg",
                    ceil_mode, exclusive, data_format=data_format)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 3, "avg",
                    ceil_mode, exclusive, data_format=data_format)


def _adaptive_pool_nd(x, output_size, nd, mode, data_format):
    channel_first = data_format.startswith("NC")
    out_sizes = _tup(output_size, nd)

    def f(a):
        spatial_start = 2 if channel_first else 1
        out = a
        # adaptive pooling = for each spatial dim, segment into output bins
        for d in range(nd):
            axis = spatial_start + d
            in_size = out.shape[axis]
            o = out_sizes[d] if out_sizes[d] is not None else in_size
            if in_size % o == 0:
                k = in_size // o
                shape = list(out.shape)
                shape[axis] = o
                shape.insert(axis + 1, k)
                r = out.reshape(shape)
                out = jnp.max(r, axis=axis + 1) if mode == "max" else jnp.mean(r, axis=axis + 1)
            else:
                # general bins via cumulative windows
                starts = (np.arange(o) * in_size) // o
                ends = ((np.arange(o) + 1) * in_size + o - 1) // o
                segs = []
                for s, e in zip(starts, ends):
                    sl = [slice(None)] * out.ndim
                    sl[axis] = slice(int(s), int(e))
                    block = out[tuple(sl)]
                    r = jnp.max(block, axis=axis, keepdims=True) if mode == "max" else jnp.mean(
                        block, axis=axis, keepdims=True
                    )
                    segs.append(r)
                out = jnp.concatenate(segs, axis=axis)
        return out

    return dispatch(f, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool_nd(x, output_size, 1, "avg", "NCL")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool_nd(x, output_size, 2, "avg", data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool_nd(x, output_size, 3, "avg", data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool_nd(x, output_size, 1, "max", "NCL")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool_nd(x, output_size, 2, "max", "NCHW")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool_nd(x, output_size, 3, "max", "NCDHW")
