"""Pooling functionals via `lax.reduce_window`.

Reference: `operators/pool_op.*`, `operators/math/pooling.cu`.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from ...core.dispatch import dispatch
from ...core.tensor import unwrap


def _tup(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(i) for i in v)
    return (int(v),) * n


def _pool_nd(x, kernel, stride, padding, nd, mode, ceil_mode=False,
             exclusive=True, data_format="NCHW"):
    kernel = _tup(kernel, nd)
    stride = _tup(stride if stride is not None else kernel, nd)
    if isinstance(padding, str):
        pad_spatial = padding.upper()
    else:
        p = _tup(padding, nd) if not isinstance(padding, (list, tuple)) or all(
            isinstance(i, int) for i in padding
        ) else padding
        if isinstance(p, tuple) and len(p) == nd:
            pad_spatial = [(i, i) for i in p]
        else:
            pad_spatial = [tuple(i) for i in p]

    channel_first = data_format.startswith("NC")

    def f(a):
        if channel_first:
            window = (1, 1) + kernel
            strides = (1, 1) + stride
        else:
            window = (1,) + kernel + (1,)
            strides = (1,) + stride + (1,)
        if isinstance(pad_spatial, str):
            pads = pad_spatial
        else:
            spatial = list(pad_spatial)
            if ceil_mode:
                # ceil output size: extend the right padding so
                # reduce_window emits ceil((in+2p-k)/s)+1 windows
                # (reference pool ceil_mode semantics)
                sp_start = 2 if channel_first else 1
                for d in range(nd):
                    i = a.shape[sp_start + d]
                    lo, hi = spatial[d]
                    num = i + lo + hi - kernel[d]
                    ceil_out = -(-num // stride[d]) + 1
                    need = (ceil_out - 1) * stride[d] + kernel[d] \
                        - (i + lo + hi)
                    if need > 0:
                        spatial[d] = (lo, hi + need)
            if channel_first:
                pads = [(0, 0), (0, 0)] + spatial
            else:
                pads = [(0, 0)] + spatial + [(0, 0)]
        if mode == "max":
            init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else jnp.iinfo(a.dtype).min
            return lax.reduce_window(a, init, lax.max, window, strides, pads)
        # avg
        summed = lax.reduce_window(a.astype(jnp.float32), 0.0, lax.add, window, strides, pads)
        if exclusive and not isinstance(pads, str):
            ones = jnp.ones_like(a, dtype=jnp.float32)
            counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
            return (summed / counts).astype(a.dtype)
        return (summed / float(np.prod(kernel))).astype(a.dtype)

    return dispatch(f, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    return _pool_nd(x, kernel_size, stride, padding, 1, "max", ceil_mode,
                    data_format="NCL")


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    if return_mask:
        return _max_pool2d_with_index(x, kernel_size, stride, padding,
                                      ceil_mode, data_format)
    return _pool_nd(x, kernel_size, stride, padding, 2, "max", ceil_mode,
                    data_format=data_format)


def _max_pool2d_with_index(x, kernel_size, stride, padding, ceil_mode,
                           data_format):
    """reference `max_pool2d_with_index` (`operators/pool_with_index_op.*`):
    also returns the argmax position of each window, flattened into the
    input's H*W plane (what max_unpool2d consumes)."""
    ks = _tup(kernel_size, 2)
    st = _tup(stride or kernel_size, 2)
    pd = _tup(padding, 2)
    nhwc = data_format == "NHWC"

    def f(a):
        if nhwc:
            a = a.transpose(0, 3, 1, 2)
        n, c, h, w = a.shape

        def osize(i, k, p, s):
            num = i + 2 * p - k
            return (num + s - 1) // s + 1 if ceil_mode else num // s + 1

        oh = osize(h, ks[0], pd[0], st[0])
        ow = osize(w, ks[1], pd[1], st[1])
        # ceil_mode may read past the padded edge: extend with -inf
        extra_h = max((oh - 1) * st[0] + ks[0] - (h + 2 * pd[0]), 0)
        extra_w = max((ow - 1) * st[1] + ks[1] - (w + 2 * pd[1]), 0)
        neg = jnp.finfo(a.dtype).min
        ap = jnp.pad(a, ((0, 0), (0, 0), (pd[0], pd[0] + extra_h),
                         (pd[1], pd[1] + extra_w)), constant_values=neg)
        hh = jnp.arange(oh)[:, None] * st[0] + jnp.arange(ks[0])[None, :]
        ww = jnp.arange(ow)[:, None] * st[1] + jnp.arange(ks[1])[None, :]
        # windows [N, C, OH, OW, KH, KW]
        win = ap[:, :, hh[:, None, :, None], ww[None, :, None, :]]
        flat = win.reshape(n, c, oh, ow, -1)
        arg = jnp.argmax(flat, axis=-1)
        out = jnp.take_along_axis(flat, arg[..., None], axis=-1)[..., 0]
        kh = arg // ks[1]
        kw = arg % ks[1]
        gh = jnp.arange(oh)[None, None, :, None] * st[0] + kh - pd[0]
        gw = jnp.arange(ow)[None, None, None, :] * st[1] + kw - pd[1]
        idx = (gh * w + gw).astype(jnp.int64)
        if nhwc:
            out = out.transpose(0, 2, 3, 1)
            idx = idx.transpose(0, 2, 3, 1)
        return out, idx

    return dispatch(f, x)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 3, "max", ceil_mode,
                    data_format=data_format)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool_nd(x, kernel_size, stride, padding, 1, "avg",
                    ceil_mode, exclusive, data_format="NCL")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 2, "avg",
                    ceil_mode, exclusive, data_format=data_format)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 3, "avg",
                    ceil_mode, exclusive, data_format=data_format)


def _adaptive_pool_nd(x, output_size, nd, mode, data_format):
    channel_first = data_format.startswith("NC")
    out_sizes = _tup(output_size, nd)

    def f(a):
        spatial_start = 2 if channel_first else 1
        out = a
        # adaptive pooling = for each spatial dim, segment into output bins
        for d in range(nd):
            axis = spatial_start + d
            in_size = out.shape[axis]
            o = out_sizes[d] if out_sizes[d] is not None else in_size
            if in_size % o == 0:
                k = in_size // o
                shape = list(out.shape)
                shape[axis] = o
                shape.insert(axis + 1, k)
                r = out.reshape(shape)
                out = jnp.max(r, axis=axis + 1) if mode == "max" else jnp.mean(r, axis=axis + 1)
            else:
                # general bins via cumulative windows
                starts = (np.arange(o) * in_size) // o
                ends = ((np.arange(o) + 1) * in_size + o - 1) // o
                segs = []
                for s, e in zip(starts, ends):
                    sl = [slice(None)] * out.ndim
                    sl[axis] = slice(int(s), int(e))
                    block = out[tuple(sl)]
                    r = jnp.max(block, axis=axis, keepdims=True) if mode == "max" else jnp.mean(
                        block, axis=axis, keepdims=True
                    )
                    segs.append(r)
                out = jnp.concatenate(segs, axis=axis)
        return out

    return dispatch(f, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool_nd(x, output_size, 1, "avg", "NCL")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool_nd(x, output_size, 2, "avg", data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool_nd(x, output_size, 3, "avg", data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool_nd(x, output_size, 1, "max", "NCL")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool_nd(x, output_size, 2, "max", "NCHW")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool_nd(x, output_size, 3, "max", "NCDHW")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCHW", name=None):
    """Inverse of max_pool2d with indices (`operators/unpool_op.*`):
    scatters each pooled value back to its argmax position."""
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size, kernel_size)
    stride = stride or kernel_size
    if isinstance(stride, int):
        stride = (stride, stride)

    def f(xv, idx):
        n, c, h, w = xv.shape
        if output_size is not None:
            oh, ow = output_size[-2:]
        else:
            oh = (h - 1) * stride[0] + kernel_size[0] - 2 * (
                padding if isinstance(padding, int) else padding[0])
            ow = (w - 1) * stride[1] + kernel_size[1] - 2 * (
                padding if isinstance(padding, int) else padding[1])
        flat = jnp.zeros((n, c, oh * ow), xv.dtype)
        nidx = jnp.arange(n)[:, None, None]
        cidx = jnp.arange(c)[None, :, None]
        flat = flat.at[nidx, cidx, idx.reshape(n, c, -1)].set(
            xv.reshape(n, c, -1))
        return flat.reshape(n, c, oh, ow)

    return dispatch(f, x, indices, nondiff=(1,))


def spatial_pyramid_pool(x, pyramid_height, pool_type="max", name=None):
    """SPP (`operators/spp_op.*`): concat adaptive {max,avg} pools at
    1x1, 2x2, ... 2^(H-1) x 2^(H-1) bins, flattened per level."""
    outs = []
    for level in range(int(pyramid_height)):
        bins = 2 ** level
        if pool_type == "max":
            p = adaptive_max_pool2d(x, bins)
        else:
            p = adaptive_avg_pool2d(x, bins)
        outs.append(p.reshape([p.shape[0], -1]))
    from ...ops import concat as _concat

    return _concat(outs, axis=1)


spp = spatial_pyramid_pool
