"""Normalization functionals.

Reference: `operators/batch_norm_op.*`, `layer_norm_op.*`, `group_norm_op.*`,
`instance_norm_op.*`, `norm_op.*` (all cudnn/CUDA); here each is a few fused
VPU lines.  Norm statistics are AMP-black (fp32).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import BLACK, dispatch
from ...core.tensor import Tensor, unwrap


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def f(a):
        n = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(n, epsilon)

    return dispatch(f, x)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-05,
               data_format="NCHW", use_global_stats=None, name=None):
    c_axis = 1 if data_format.startswith("NC") else unwrap(x).ndim - 1
    reduce_axes = tuple(i for i in range(unwrap(x).ndim) if i != c_axis)
    use_batch_stats = training and not use_global_stats

    bshape = [1] * unwrap(x).ndim
    bshape[c_axis] = -1

    if use_batch_stats:
        def f(a, *wb):
            # stats in f32 (AMP-black), but the *activation* stays in the
            # input dtype: folding scale/rsqrt into one per-channel a,b
            # keeps the application a single fused x*s+t in a.dtype —
            # emitting f32 out of BN doubled the HBM traffic of every
            # downstream relu/residual/conv-recast under bf16 AMP
            # (measured 2190->1708 imgs/s on ResNet50, round-3 probe)
            mean = jnp.mean(a.astype(jnp.float32), axis=reduce_axes)
            var = jnp.var(a.astype(jnp.float32), axis=reduce_axes)
            rstd = jax.lax.rsqrt(var + epsilon)
            if wb:
                s = wb[0] * rstd
                t = wb[1] - mean * s
            else:
                s = rstd
                t = -mean * rstd
            return a * s.reshape(bshape).astype(a.dtype) + \
                t.reshape(bshape).astype(a.dtype)

        # update running stats eagerly (buffers; reference batch_norm_op
        # updates MeanOut/VarianceOut in the same kernel)
        a = unwrap(x).astype(jnp.float32)
        mean = jnp.mean(a, axis=reduce_axes)
        var = jnp.var(a, axis=reduce_axes)
        if running_mean is not None and isinstance(running_mean, Tensor):
            # set_value handles the in-trace case (records the write as a
            # compiled-program output) — no exception guard needed
            running_mean.set_value(
                momentum * running_mean._array + (1 - momentum) * mean
            )
            running_var.set_value(
                momentum * running_var._array + (1 - momentum) * var
            )
    else:
        rm, rv = unwrap(running_mean), unwrap(running_var)

        def f(a, *wb):
            rstd = jax.lax.rsqrt(rv.astype(jnp.float32) + epsilon)
            if wb:
                s = wb[0] * rstd
                t = wb[1] - rm * s
            else:
                s = rstd
                t = -rm * rstd
            return a * s.reshape(bshape).astype(a.dtype) + \
                t.reshape(bshape).astype(a.dtype)

    if weight is not None:
        return dispatch(f, x, weight, bias)
    return dispatch(f, x)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    nd = len(normalized_shape)
    axes = tuple(range(-nd, 0))

    if weight is not None and bias is not None and nd == 1:
        # common single-axis case: fused Pallas kernel on TPU (XLA composed
        # form elsewhere) — reference fused layer_norm CUDA kernels
        from ...ops.pallas.layer_norm import fused_layer_norm

        def f1(a, w, b):
            shape = a.shape
            out = fused_layer_norm(a.reshape(-1, shape[-1]), w, b, epsilon)
            return out.reshape(shape)

        return dispatch(f1, x, weight, bias)

    def f(a, *wb):
        a32 = a.astype(jnp.float32)
        mean = jnp.mean(a32, axis=axes, keepdims=True)
        var = jnp.var(a32, axis=axes, keepdims=True)
        out = ((a32 - mean) * jax.lax.rsqrt(var + epsilon)).astype(a.dtype)
        if wb:
            out = out * wb[0].astype(a.dtype) + wb[1].astype(a.dtype)
        return out

    if weight is not None:
        return dispatch(f, x, weight, bias)
    return dispatch(f, x)


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW", name=None):
    c_axis = 1 if data_format.startswith("NC") else unwrap(x).ndim - 1

    def f(a, *wb):
        shape = a.shape
        if c_axis != 1:
            a = jnp.moveaxis(a, c_axis, 1)
        n, c = a.shape[0], a.shape[1]
        g = a.reshape(n, num_groups, c // num_groups, *a.shape[2:])
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(g.astype(jnp.float32), axis=axes, keepdims=True)
        out = ((g.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + epsilon)).astype(a.dtype)
        out = out.reshape(a.shape)
        if wb:
            bshape = [1, c] + [1] * (a.ndim - 2)
            out = out * wb[0].reshape(bshape).astype(a.dtype) + \
                wb[1].reshape(bshape).astype(a.dtype)
        if c_axis != 1:
            out = jnp.moveaxis(out, 1, c_axis)
        return out

    if weight is not None:
        return dispatch(f, x, weight, bias)
    return dispatch(f, x)


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-05,
                  data_format="NCHW", name=None):
    nd = unwrap(x).ndim
    channel_first = data_format.startswith("NC")
    # per-(sample, channel) stats over the spatial dims only
    axes = tuple(range(2, nd)) if channel_first else tuple(range(1, nd - 1))

    def f(a, *wb):
        mean = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = ((a.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + eps)).astype(a.dtype)
        if wb:
            if channel_first:
                bshape = [1, -1] + [1] * (nd - 2)
            else:
                bshape = [1] * (nd - 1) + [-1]
            out = out * wb[0].reshape(bshape).astype(a.dtype) + \
                wb[1].reshape(bshape).astype(a.dtype)
        return out

    if weight is not None:
        return dispatch(f, x, weight, bias)
    return dispatch(f, x)


def local_response_norm(x, size, alpha=0.0001, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def f(a):
        # across-channel LRN (reference operators/lrn_op.cc)
        c_axis = 1 if data_format.startswith("NC") else a.ndim - 1
        sq = jnp.square(a)
        half = size // 2
        pads = [(0, 0)] * a.ndim
        pads[c_axis] = (half, size - half - 1)
        padded = jnp.pad(sq, pads)
        # sliding window sum over channel axis
        acc = jnp.zeros_like(a)
        for i in range(size):
            idx = [slice(None)] * a.ndim
            idx[c_axis] = slice(i, i + a.shape[c_axis])
            acc = acc + padded[tuple(idx)]
        div = jnp.power(k + alpha * acc, beta)
        return a / div

    return dispatch(f, x)
