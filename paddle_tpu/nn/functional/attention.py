"""Attention functionals.

Reference: the inference-only fused `multihead_matmul` op
(`operators/fused/multihead_matmul_op.cu`) and the python-composed attention
in `python/paddle/nn/layer/transformer.py`.  TPU-native: a single fused
scaled-dot-product attention that XLA maps onto MXU matmuls; on TPU the inner
kernel is replaced by the Pallas flash-attention kernel
(`paddle_tpu/ops/pallas/flash_attention.py`) when shapes allow.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import WHITE, dispatch
from ...core.tensor import unwrap


def _sdpa_reference(q, k, v, mask, dropout_p, scale, is_causal):
    # q,k,v: [B, N, H, D] (paddle transformer convention is [B, H, N, D] after
    # transpose; we accept [B, H, N, D] here)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / jnp.sqrt(d).astype(q.dtype)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * s
    logits = logits.astype(jnp.float32)
    if is_causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        causal = jnp.tril(jnp.ones((ql, kl), dtype=bool), k=kl - ql)
        logits = jnp.where(causal, logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def multi_query_causal_mask(q_offsets, q_len, seq_lens, kv_len):
    """Ragged multi-query causal visibility, shared by the paged
    attention reference (`ops.pallas.paged_attention`) and the
    speculative-decode verify step so the two can never disagree.

    Query ``i`` of sequence ``b`` sits at absolute position
    ``q_offsets[b] + i`` and may see KV position ``p`` iff
    ``p < min(seq_lens[b], q_offsets[b] + i + 1)`` — bottom-right-aligned
    causality clamped to the sequence's live KV range (``seq_lens`` may
    be below the last query's position when trailing KV writes were
    suppressed, e.g. past a request's token budget).

    q_offsets/seq_lens: [B] int32; returns bool [B, q_len, kv_len].
    A sequence with ``seq_lens == 0`` is fully masked (inactive slot).
    """
    pos = jnp.arange(kv_len, dtype=jnp.int32)
    qi = jnp.arange(q_len, dtype=jnp.int32)
    limit = jnp.minimum(seq_lens[:, None],
                        q_offsets[:, None] + qi[None, :] + 1)  # [B, Q]
    return pos[None, None, :] < limit[:, :, None]


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, scale=None,
                                 training=True, name=None):
    """query/key/value: [batch, num_heads, seq, head_dim]."""
    use_pallas = False
    try:
        q_arr = unwrap(query)
        if q_arr.ndim == 4 and jax.default_backend() == "tpu":
            use_pallas = True
    except Exception:
        pass

    if use_pallas:
        from ...ops.pallas.flash_attention import flash_attention_fwd

        def f(q, k, v, *m):
            return flash_attention_fwd(q, k, v, m[0] if m else None, is_causal, scale)

    else:
        def f(q, k, v, *m):
            return _sdpa_reference(q, k, v, m[0] if m else None, dropout_p, scale, is_causal)

    if attn_mask is not None:
        return dispatch(f, query, key, value, attn_mask, nondiff=(3,), amp_policy=WHITE)
    return dispatch(f, query, key, value, amp_policy=WHITE)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, name=None):
    out = scaled_dot_product_attention(query, key, value, is_causal=causal,
                                       dropout_p=dropout)
    if return_softmax:
        return out, None
    return out
