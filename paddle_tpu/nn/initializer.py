"""Weight initializers.

Reference: `python/paddle/fluid/initializer.py:99-869` (Constant, Uniform,
Normal, TruncatedNormal, Xavier, MSRA/Kaiming, Bilinear, NumpyArrayInitializer)
re-exported as `paddle.nn.initializer`.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtype_mod
from ..core import framework


def _fan_in_out(shape):
    shape = list(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def _init(self, shape, dtype):
        raise NotImplementedError

    def __call__(self, param, block=None):
        param.set_value(self._init(param.shape, param.dtype))
        return param


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def _init(self, shape, dtype):
        return jnp.full(shape, self.value, dtype=dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def _init(self, shape, dtype):
        key = framework.get_rng_key()
        return jax.random.uniform(key, shape, dtype, self.low, self.high)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def _init(self, shape, dtype):
        key = framework.get_rng_key()
        return jax.random.normal(key, shape, dtype) * self.std + self.mean


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def _init(self, shape, dtype):
        key = framework.get_rng_key()
        return (
            jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype) * self.std
            + self.mean
        )


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _init(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        key = framework.get_rng_key()
        return jax.random.uniform(key, shape, dtype, -limit, limit)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _init(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        key = framework.get_rng_key()
        return jax.random.normal(key, shape, dtype) * std


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu", name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def _init(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope**2))
        limit = gain * math.sqrt(3.0 / fi)
        key = framework.get_rng_key()
        return jax.random.uniform(key, shape, dtype, -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu", name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def _init(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope**2))
        std = gain / math.sqrt(fi)
        key = framework.get_rng_key()
        return jax.random.normal(key, shape, dtype) * std


MSRAInitializer = KaimingNormal


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def _init(self, shape, dtype):
        arr = jnp.asarray(np.asarray(self.value), dtype=dtype)
        if list(arr.shape) != list(shape):
            arr = arr.reshape(shape)
        return arr


NumpyArrayInitializer = Assign


class Bilinear(Initializer):
    """Bilinear upsampling kernel init (reference initializer.py:693)."""

    def _init(self, shape, dtype):
        weight = np.zeros(shape, dtype="float32")
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(np.prod(shape[2:])):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            v = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
            weight[:, :, y, x] = v
        return jnp.asarray(weight, dtype=dtype)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def _init(self, shape, dtype):
        key = framework.get_rng_key()
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        a = jax.random.normal(key, (max(rows, cols), min(rows, cols)), jnp.float32)
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dtype)


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def _init(self, shape, dtype):
        w = np.zeros(shape, dtype="float32")
        out_per_group = shape[0] // self.groups
        for g in range(self.groups):
            for i in range(min(out_per_group, shape[1])):
                idx = (g * out_per_group + i, i) + tuple(s // 2 for s in shape[2:])
                w[idx] = 1.0
        return jnp.asarray(w, dtype=dtype)


def calculate_gain(nonlinearity, param=None):
    if nonlinearity in ("sigmoid", "linear", "conv1d", "conv2d", "conv3d"):
        return 1.0
    if nonlinearity == "tanh":
        return 5.0 / 3
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = param if param is not None else 0.01
        return math.sqrt(2.0 / (1 + a**2))
    if nonlinearity == "selu":
        return 3.0 / 4
    raise ValueError(nonlinearity)
