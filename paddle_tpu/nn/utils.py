"""paddle.nn.utils — weight reparameterizations.

Reference: `python/paddle/nn/utils/weight_norm_hook.py` and
`operators/spectral_norm_op.*` (power-iteration spectral normalization).
TPU-native: the reparameterized weight is recomputed in forward via a
pre-forward hook (pure function of the stored params), so it traces
cleanly into compiled steps.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, unwrap
from .layer.layers import Layer

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm"]


def _norm_except(w, dim):
    axes = tuple(i for i in range(w.ndim) if i != dim)
    return jnp.sqrt(jnp.square(w).sum(axis=axes, keepdims=True))


def weight_norm(layer: Layer, name="weight", dim=0):
    """w = g * v / ||v|| (reference weight_norm_hook.py).  Adds
    `{name}_g` / `{name}_v` params and recomputes `{name}` before every
    forward."""
    w = getattr(layer, name)
    # dim=None means a single whole-tensor norm (reference
    # weight_norm_hook.py); _norm_except reduces over all axes for None.
    g = Tensor(_norm_except(unwrap(w), dim))
    v = Tensor(unwrap(w))
    g.stop_gradient = False
    v.stop_gradient = False
    setattr(layer, name + "_g", g)
    setattr(layer, name + "_v", v)
    layer._parameters[name + "_g"] = g
    layer._parameters[name + "_v"] = v
    layer._parameters.pop(name, None)

    def hook(ly, inputs):
        vv = unwrap(getattr(ly, name + "_v"))
        gg = unwrap(getattr(ly, name + "_g"))
        wt = Tensor(gg * vv / jnp.maximum(_norm_except(vv, dim), 1e-12))
        object.__setattr__(ly, name, wt)
        return inputs

    handle = layer.register_forward_pre_hook(hook)
    layer._weight_norm_handle = handle
    layer._weight_norm_dim = dim
    hook(layer, ())
    return layer


def remove_weight_norm(layer: Layer, name="weight"):
    handle = getattr(layer, "_weight_norm_handle", None)
    if handle is not None:
        handle.remove()
    dim = getattr(layer, "_weight_norm_dim", 0)
    v = layer._parameters.pop(name + "_v", None)
    g = layer._parameters.pop(name + "_g", None)
    if v is not None and g is not None:
        w = Tensor(unwrap(g) * unwrap(v)
                   / jnp.maximum(_norm_except(unwrap(v), dim), 1e-12))
        w.stop_gradient = False
        layer._parameters[name] = w
        setattr(layer, name, w)
    for attr in (name + "_g", name + "_v"):
        if hasattr(layer, attr):
            delattr(layer, attr)
    return layer


def spectral_norm(layer: Layer, name="weight", n_power_iterations=1,
                  eps=1e-12, dim=None):
    """w_sn = w / sigma_max(w) with power-iteration u/v buffers
    (reference `operators/spectral_norm_op.h`)."""
    w = getattr(layer, name)
    warr = unwrap(w)
    if dim is None:
        dim = 0
    mat = jnp.moveaxis(warr, dim, 0).reshape(warr.shape[dim], -1)
    rng = np.random.RandomState(0)
    u = Tensor(jnp.asarray(rng.randn(mat.shape[0]).astype(np.float32)))
    v = Tensor(jnp.asarray(rng.randn(mat.shape[1]).astype(np.float32)))
    orig = Tensor(warr)
    orig.stop_gradient = False
    layer._parameters[name + "_orig"] = orig
    layer._parameters.pop(name, None)
    setattr(layer, name + "_orig", orig)
    setattr(layer, name + "_u", u)
    setattr(layer, name + "_v", v)

    def hook(ly, inputs):
        wv = unwrap(getattr(ly, name + "_orig"))
        m = jnp.moveaxis(wv, dim, 0).reshape(wv.shape[dim], -1)
        uu = unwrap(getattr(ly, name + "_u"))
        vv = unwrap(getattr(ly, name + "_v"))
        for _ in range(max(1, n_power_iterations)):
            vv = m.T @ uu
            vv = vv / jnp.maximum(jnp.linalg.norm(vv), eps)
            uu = m @ vv
            uu = uu / jnp.maximum(jnp.linalg.norm(uu), eps)
        sigma = uu @ (m @ vv)
        wt = Tensor(wv / jnp.maximum(sigma, eps))
        object.__setattr__(ly, name, wt)
        # persist the iteration state (buffers, not differentiated)
        getattr(ly, name + "_u")._array = jax.lax.stop_gradient(uu)
        getattr(ly, name + "_v")._array = jax.lax.stop_gradient(vv)
        return inputs

    handle = layer.register_forward_pre_hook(hook)
    layer._spectral_norm_handle = handle
    hook(layer, ())
    return layer
