"""RNN layers.

Reference: `python/paddle/nn/layer/rnn.py` (RNNCellBase, SimpleRNNCell,
LSTMCell, GRUCell, RNN, BiRNN, SimpleRNN/LSTM/GRU) with CUDA kernels in
`operators/rnn_op.*`/cudnn LSTM.  TPU-native: the time loop is a
`lax.scan`, which XLA compiles to a single fused loop — no cudnn descriptor
machinery, and the whole multi-layer stack jits into one computation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import dispatch
from ...core.tensor import Tensor, unwrap
from .. import functional as F
from .. import initializer as init
from .layers import Layer


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        from ...ops import zeros

        batch = batch_ref.shape[batch_dim_idx]
        shape = shape or self.state_shape
        if isinstance(shape[0], (list, tuple)):
            return tuple(zeros([batch] + list(s), dtype="float32") for s in shape)
        return zeros([batch] + list(shape), dtype="float32")


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / np.sqrt(hidden_size)
        u = init.Uniform(-std, std)
        self.weight_ih = self.create_parameter([hidden_size, input_size],
                                               weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size],
                                               weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter([hidden_size], bias_ih_attr,
                                             is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter([hidden_size], bias_hh_attr,
                                             is_bias=True, default_initializer=u)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def f(x, h, wi, wh, bi, bh):
            return act(x @ wi.T + bi + h @ wh.T + bh)

        h = dispatch(f, inputs, states, self.weight_ih, self.weight_hh,
                     self.bias_ih, self.bias_hh)
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / np.sqrt(hidden_size)
        u = init.Uniform(-std, std)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size],
                                               weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size],
                                               weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter([4 * hidden_size], bias_ih_attr,
                                             is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter([4 * hidden_size], bias_hh_attr,
                                             is_bias=True, default_initializer=u)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states

        def f(x, hh, cc, wi, wh, bi, bh):
            gates = x @ wi.T + bi + hh @ wh.T + bh
            i, fo, g, o = jnp.split(gates, 4, axis=-1)
            i, fo, o = jax.nn.sigmoid(i), jax.nn.sigmoid(fo), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = fo * cc + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new

        h_new, c_new = dispatch(f, inputs, h, c, self.weight_ih, self.weight_hh,
                                self.bias_ih, self.bias_hh)
        return h_new, (h_new, c_new)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / np.sqrt(hidden_size)
        u = init.Uniform(-std, std)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size],
                                               weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size],
                                               weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter([3 * hidden_size], bias_ih_attr,
                                             is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter([3 * hidden_size], bias_hh_attr,
                                             is_bias=True, default_initializer=u)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def f(x, h, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = h @ wh.T + bh
            ir, iz, ig = jnp.split(gi, 3, axis=-1)
            hr, hz, hg = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            g = jnp.tanh(ig + r * hg)
            return (1 - z) * g + z * h

        h = dispatch(f, inputs, states, self.weight_ih, self.weight_hh,
                     self.bias_ih, self.bias_hh)
        return h, h


class RNN(Layer):
    """Run a cell over time (reference rnn.py class RNN); the loop is python
    in eager mode but fuses into one XLA while-loop under jit."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops import stack

        time_axis = 0 if self.time_major else 1
        steps = inputs.shape[time_axis]
        states = initial_states
        outputs = []
        order = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        for t in order:
            x_t = inputs[:, t] if time_axis == 1 else inputs[t]
            out, states = self.cell(x_t, states)
            outputs.append(out)
        if self.is_reverse:
            outputs = outputs[::-1]
        out = stack(outputs, axis=time_axis)
        return out, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops import concat

        s_fw, s_bw = (initial_states if initial_states is not None else (None, None))
        out_fw, st_fw = self.rnn_fw(inputs, s_fw)
        out_bw, st_bw = self.rnn_bw(inputs, s_bw)
        return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        from .container import LayerList

        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout
        bidirect = 2 if direction in ("bidirect", "bidirectional") else 1
        self.num_directions = bidirect

        Cell = {"RNN_TANH": SimpleRNNCell, "RNN_RELU": SimpleRNNCell,
                "LSTM": LSTMCell, "GRU": GRUCell}[mode]
        kwargs = {}
        if mode == "RNN_RELU":
            kwargs["activation"] = "relu"
        elif mode == "RNN_TANH":
            kwargs["activation"] = "tanh"

        self._all_layers = LayerList()
        for layer in range(num_layers):
            in_size = input_size if layer == 0 else hidden_size * bidirect
            if bidirect == 2:
                self._all_layers.append(BiRNN(
                    Cell(in_size, hidden_size, **kwargs),
                    Cell(in_size, hidden_size, **kwargs), time_major))
            else:
                self._all_layers.append(RNN(
                    Cell(in_size, hidden_size, **kwargs),
                    time_major=time_major))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops import stack
        from ...static import export_marker

        if (export_marker.export_tracing() and initial_states is None
                and sequence_length is None):
            return self._export_forward(inputs)
        out = inputs
        final_h, final_c = [], []
        for i, rnn_l in enumerate(self._all_layers):
            out, st = rnn_l(out)
            if self.mode == "LSTM":
                if self.num_directions == 2:
                    (h_f, c_f), (h_b, c_b) = st
                    final_h += [h_f, h_b]
                    final_c += [c_f, c_b]
                else:
                    h, c = st
                    final_h.append(h)
                    final_c.append(c)
            else:
                if self.num_directions == 2:
                    final_h += list(st)
                else:
                    final_h.append(st)
            if self.dropout > 0 and i < len(self._all_layers) - 1:
                out = F.dropout(out, self.dropout, training=self.training)
        h_stack = stack(final_h, axis=0)
        if self.mode == "LSTM":
            return out, (h_stack, stack(final_c, axis=0))
        return out, h_stack

    def _export_forward(self, inputs):
        """Serialize-time lowering: bind the `paddle_rnn` marker so the
        exporter emits ONE unified `rnn` op (`operators/rnn_op.cc`) —
        the same single-fused-op form the reference's `nn.LSTM` always
        executes — instead of unrolling the python time loop into T
        cell copies.  Weight order matches the op's WeightList contract:
        (w_ih, w_hh) per (layer, direction), then (b_ih, b_hh) per
        (layer, direction)."""
        from ...static.export_marker import rnn_p

        x = unwrap(inputs)
        nd = self.num_directions
        cells = []
        for lyr in self._all_layers:
            if nd == 2:
                cells += [lyr.rnn_fw.cell, lyr.rnn_bw.cell]
            else:
                cells.append(lyr.cell)
        ws = [unwrap(w) for c in cells
              for w in (c.weight_ih, c.weight_hh)]
        bs = [unwrap(b) for c in cells
              for b in (c.bias_ih, c.bias_hh)]
        batch = x.shape[1] if self.time_major else x.shape[0]
        h0 = jnp.zeros((self.num_layers * nd, batch, self.hidden_size),
                       x.dtype)
        outs = rnn_p.bind(x, h0, jnp.zeros_like(h0), *ws, *bs,
                          mode=self.mode, hidden_size=self.hidden_size,
                          num_layers=self.num_layers,
                          is_bidirec=(nd == 2),
                          time_major=self.time_major,
                          dropout=float(self.dropout))
        if self.mode == "LSTM":
            out, h, c = outs
            return Tensor(out), (Tensor(h), Tensor(c))
        out, h = outs
        return Tensor(out), Tensor(h)


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(mode, input_size, hidden_size, num_layers, direction,
                         time_major, dropout)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout)
