"""Loss layer classes (reference `python/paddle/nn/layer/loss.py`)."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax

    def forward(self, input, label):
        return F.cross_entropy(input, label, self.weight, self.ignore_index,
                               self.reduction, self.soft_label, self.axis,
                               self.use_softmax)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index,
                          self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None, name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, self.weight,
                                                  self.reduction, self.pos_weight)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin, self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin,
                                       self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.args = (margin, p, epsilon, swap, reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, *self.args)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid (reference `hierarchical_sigmoid_op.*`), default
    complete-binary-tree coding."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        import math

        self.num_classes = num_classes
        self.code_length = int(math.ceil(math.log2(num_classes)))
        self.weight = self.create_parameter([num_classes - 1, feature_size],
                                            attr=weight_attr)
        self.bias = self.create_parameter([num_classes - 1], attr=bias_attr,
                                          is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        import jax.numpy as jnp

        from ...core.dispatch import dispatch

        L = self.code_length

        def f(x, w, b, y):
            y = y.reshape(-1).astype(jnp.int32)
            # complete binary tree: internal node ids along the path of y
            code = y + self.num_classes  # leaf position in heap order
            losses = 0.0
            for _ in range(L):
                parent = code // 2
                bit = (code % 2).astype(jnp.float32)  # 1 = right child
                valid = parent >= 1
                node = jnp.clip(parent - 1, 0, self.num_classes - 2)
                logit = jnp.sum(x * w[node], axis=-1) + b[node]
                # right child -> target 1
                lo = bit * jnp.logaddexp(0.0, -logit) + (1 - bit) * jnp.logaddexp(0.0, logit)
                losses = losses + jnp.where(valid, lo, 0.0)
                code = parent
            return jnp.mean(losses)

        return dispatch(f, input, self.weight, self.bias, label, nondiff=(3,))
