"""Transformer layers.

Reference: `python/paddle/nn/layer/transformer.py` (MultiHeadAttention with
cache, TransformerEncoder/DecoderLayer, Transformer).  TPU-native: the
attention core routes through `F.scaled_dot_product_attention` (Pallas flash
attention on TPU) instead of composing matmul+softmax ops.
"""
from __future__ import annotations

import collections

# module level, NOT function-local: the op fns below close over these
# names; a function-local `import jax` would put the MODULE object in a
# closure cell, which the dispatch fingerprinter rejects as uncacheable
# — every call would bypass the executable cache (review round 10)
import jax
import jax.numpy as jnp

from ...core.dispatch import dispatch
from ...core.tensor import Tensor, unwrap
from .. import functional as F
from .common import Dropout, Linear
from .layers import Layer
from .norm import LayerNorm


def _convert_attention_mask(attn_mask, dtype):
    return attn_mask


# Preallocated KV cache (shared container + write/mask helpers):
# `gen_cache(..., max_length=)` allocates the K/V buffers ONCE at the
# decode horizon and every step writes its new rows via
# `lax.dynamic_update_slice`.  The win over the concat-growth cache is
# SHAPE STABILITY: every decode step has the same signature, so steps
# 2..N replay one cached executable instead of retracing (concat's
# growing shapes missed the dispatch cache every token).  The write
# itself still produces a fresh XLA buffer (the generic dispatch path
# cannot donate operands) — true in-place updates belong to the paged
# serving engine, whose jitted step donates its page pools.  `length` is
# a 0-d int32 Tensor (an array operand, not a python scalar) so all
# decode steps share ONE cached executable per signature.
PreallocKVCache = collections.namedtuple("PreallocKVCache",
                                         ["k", "v", "length"])


def kv_capacity_check(length, s_new, max_length):
    """Loud eager-mode overflow check: dynamic_update_slice would CLAMP
    an out-of-range start onto the last rows and kv_valid_mask would
    expose the whole (stale) buffer — silent corruption.  Under a jit
    trace the length is abstract and the caller owns the horizon
    (GPT.generate and DecodeEngine both guard theirs).  NOTE: reading
    the length forces a host sync — callers writing several buffers at
    one position should check once and pass check_capacity=False to the
    writes."""
    ln = unwrap(length)
    if not isinstance(ln, jax.core.Tracer) and \
            int(ln) + s_new > max_length:
        raise ValueError(
            f"PreallocKVCache overflow: writing {s_new} rows at "
            f"position {int(ln)} exceeds max_length {max_length}")


def kv_cache_write(buf, new, length, check_capacity=True):
    """Write `new` [B,H,s,D] into `buf` [B,H,Smax,D] at row `length`
    (0-d int32 Tensor) — shape-stable for the dispatch cache (the
    returned buffer is a fresh XLA allocation; see the module
    comment)."""
    if check_capacity:
        kv_capacity_check(length, new.shape[2], buf.shape[2])

    def f(b, n, s):
        return jax.lax.dynamic_update_slice(b, n.astype(b.dtype),
                                            (0, 0, s, 0))

    return dispatch(f, buf, new, length, nondiff=(2,))


def kv_valid_mask(length, s_new, max_length, causal=True):
    """Bool mask [1,1,s_new,max_length] over a preallocated KV buffer.

    ``causal=True``: key j visible to new query row i iff
    j <= length + i — buffer validity plus causality within the
    appended chunk (the decoder-block contract, used by GPT).

    ``causal=False``: key j visible iff j < length + s_new — buffer
    validity only, matching the legacy concat ``Cache`` contract where
    within-chunk causality is the caller's attn_mask's business."""

    def f(ln):
        kpos = jax.lax.broadcasted_iota(jnp.int32,
                                        (1, 1, s_new, max_length), 3)
        if causal:
            qpos = jax.lax.broadcasted_iota(jnp.int32,
                                            (1, 1, s_new, max_length), 2)
            return kpos <= ln + qpos
        return kpos < ln + s_new

    return dispatch(f, length)


def _combine_masks(valid, user_mask):
    if user_mask is None:
        return valid
    if user_mask.dtype == jnp.bool_:
        return dispatch(lambda a, b: a & b, valid, user_mask)
    # additive float mask: invalid positions forced to -1e30
    return dispatch(
        lambda a, m: jnp.where(a, m.astype(jnp.float32),
                               jnp.float32(-1e30)),
        valid, user_mask)


class MultiHeadAttention(Layer):
    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])
    PreallocCache = PreallocKVCache

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.dropout = dropout
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.need_weights = need_weights

    def _shape(self, x):
        from ...ops import reshape, transpose

        b, s = x.shape[0], x.shape[1]
        x = reshape(x, [b, s, self.num_heads, self.head_dim])
        return transpose(x, [0, 2, 1, 3])  # [B, H, S, D]

    def gen_cache(self, key, value=None, type=None, max_length=None):
        if type == MultiHeadAttention.StaticCache:
            k, v = self._shape(self.k_proj(key)), self._shape(self.v_proj(value if value is not None else key))
            return self.StaticCache(k, v)
        from ...ops import zeros

        b = key.shape[0]
        if max_length is not None:
            # preallocated cache: one buffer for the whole decode
            # horizon, written via dynamic_update_slice
            k = zeros([b, self.num_heads, int(max_length), self.head_dim],
                      dtype=str(key.dtype))
            v = zeros([b, self.num_heads, int(max_length), self.head_dim],
                      dtype=str(key.dtype))
            return self.PreallocCache(k, v, Tensor(jnp.zeros((), jnp.int32)))
        k = zeros([b, self.num_heads, 0, self.head_dim], dtype=str(key.dtype))
        v = zeros([b, self.num_heads, 0, self.head_dim], dtype=str(key.dtype))
        return self.Cache(k, v)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        from ...ops import concat, reshape, transpose

        key = query if key is None else key
        value = key if value is None else value

        q = self._shape(self.q_proj(query))
        if isinstance(cache, self.StaticCache):
            k, v = cache.k, cache.v
        else:
            k = self._shape(self.k_proj(key))
            v = self._shape(self.v_proj(value))
            if isinstance(cache, self.PreallocCache):
                s_new = k.shape[2]
                # one capacity check (host sync) covers both writes
                kv_capacity_check(cache.length, s_new, cache.k.shape[2])
                full_k = kv_cache_write(cache.k, k, cache.length,
                                        check_capacity=False)
                full_v = kv_cache_write(cache.v, v, cache.length,
                                        check_capacity=False)
                # buffer-validity only (causal=False): the legacy Cache
                # contract leaves within-chunk causality to the caller's
                # attn_mask, and a drop-in replacement must too
                valid = kv_valid_mask(cache.length, s_new,
                                      full_k.shape[2], causal=False)
                attn_mask = _combine_masks(valid, attn_mask)
                k, v = full_k, full_v
                cache = self.PreallocCache(full_k, full_v,
                                           cache.length + s_new)
            elif isinstance(cache, self.Cache):
                k = concat([cache.k, k], axis=2)
                v = concat([cache.v, v], axis=2)
                cache = self.Cache(k, v)

        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.dropout if self.training else 0.0,
        )
        out = transpose(out, [0, 2, 1, 3])
        out = reshape(out, [out.shape[0], out.shape[1], self.embed_dim])
        out = self.out_proj(out)
        if cache is not None and not isinstance(cache, self.StaticCache):
            return out, cache
        return out


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        from .container import LayerList

        self.layers = LayerList(
            [encoder_layer] + [copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)]
        )
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, src_mask)
            else:
                output, new_cache = mod(output, src_mask, cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout)
        self.linear1 = Linear(d_model, dim_feedforward)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
            incremental_cache = None
        else:
            tgt, incremental_cache = self.self_attn(tgt, tgt, tgt, tgt_mask, cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None or cache[1] is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
            static_cache = cache[1] if cache is not None else None
        else:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask, cache[1])
            static_cache = cache[1]
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        if cache is None:
            return tgt
        return tgt, (incremental_cache, static_cache)

    def gen_cache(self, memory):
        incremental = self.self_attn.gen_cache(memory)
        static = self.cross_attn.gen_cache(memory, memory,
                                           type=MultiHeadAttention.StaticCache)
        return incremental, static


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        from .container import LayerList

        self.layers = LayerList(
            [decoder_layer] + [copy.deepcopy(decoder_layer) for _ in range(num_layers - 1)]
        )
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        output = tgt
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, memory, tgt_mask, memory_mask)
            else:
                output, new_cache = mod(output, memory, tgt_mask, memory_mask, cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        cache = [layer.gen_cache(memory) for layer in self.layers]
        if do_zip:
            cache = list(zip(*cache))
        return cache


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers, enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers, dec_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    def generate_square_subsequent_mask(self, length):
        import jax.numpy as jnp

        mask = jnp.where(
            jnp.tril(jnp.ones((length, length), dtype=bool)), 0.0, -1e9
        ).astype(jnp.float32)
        return Tensor(mask)
