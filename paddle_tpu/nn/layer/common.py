"""Common layers (reference `python/paddle/nn/layer/common.py`)."""
from __future__ import annotations

from ...core import dtype as dtype_mod
from .. import functional as F
from .. import initializer as init
from .layers import Layer


class Identity(Layer):
    def forward(self, x):
        return x


class Linear(Layer):
    """y = xW + b, weight [in_features, out_features]
    (reference nn/layer/common.py Linear; kernel = matmul_v2 + elementwise_add)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=init.XavierUniform(),
        )
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                [out_features], attr=bias_attr, is_bias=True
            )

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, training=self.training,
                         mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class Embedding(Layer):
    """reference nn/layer/common.py Embedding → lookup_table_v2 op."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=init.Normal(0.0, 1.0) if weight_attr is None else None,
        )
        if padding_idx is not None:
            arr = self.weight._array.at[padding_idx].set(0.0)
            self.weight._array = arr

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ...ops import flatten

        return flatten(x, self.start_axis, self.stop_axis)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode, self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", data_format=data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True,
                         data_format=data_format)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr
        )
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True
        )

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class _PadNd(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW"):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(_PadNd):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups
        self.data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, self.output_sizes, *self.args)
