"""Activation layer classes (reference `python/paddle/nn/layer/activation.py`)."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer


def _act_layer(fn_name, **defaults):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            names = list(defaults.keys())
            self._kw = dict(defaults)
            for i, a in enumerate(args):
                self._kw[names[i]] = a
            for k, v in kwargs.items():
                if k != "name":
                    self._kw[k] = v

        def forward(self, x):
            return getattr(F, fn_name)(x, **self._kw)

        def extra_repr(self):
            return ", ".join(f"{k}={v}" for k, v in self._kw.items())

    return _Act


ReLU = _act_layer("relu")
ReLU6 = _act_layer("relu6")
Sigmoid = _act_layer("sigmoid")
LogSigmoid = _act_layer("log_sigmoid")
Tanh = _act_layer("tanh")
Tanhshrink = _act_layer("tanhshrink")
GELU = _act_layer("gelu", approximate=False)
LeakyReLU = _act_layer("leaky_relu", negative_slope=0.01)
ELU = _act_layer("elu", alpha=1.0)
CELU = _act_layer("celu", alpha=1.0)
SELU = _act_layer("selu")
Silu = _act_layer("silu")
Swish = _act_layer("swish")
Mish = _act_layer("mish")
Hardtanh = _act_layer("hardtanh", min=-1.0, max=1.0)
Hardsigmoid = _act_layer("hardsigmoid")
Hardswish = _act_layer("hardswish")
Hardshrink = _act_layer("hardshrink", threshold=0.5)
Softshrink = _act_layer("softshrink", threshold=0.5)
Softplus = _act_layer("softplus", beta=1.0, threshold=20.0)
Softsign = _act_layer("softsign")
ThresholdedReLU = _act_layer("thresholded_relu", threshold=1.0)
Softmax = _act_layer("softmax", axis=-1)
LogSoftmax = _act_layer("log_softmax", axis=-1)
Maxout = _act_layer("maxout", groups=2, axis=1)
GLU = _act_layer("glu", axis=-1)
RReLU = _act_layer("rrelu", lower=0.125, upper=1.0 / 3.0)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        from .. import initializer as I

        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init),
        )

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)
