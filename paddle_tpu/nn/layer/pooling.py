"""Pooling layer classes (reference `python/paddle/nn/layer/pooling.py`)."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer


def _pool_layer(fn_name, adaptive=False):
    class _Pool(Layer):
        def __init__(self, kernel_size=None, stride=None, padding=0,
                     output_size=None, **kwargs):
            super().__init__()
            self._adaptive = adaptive
            if adaptive:
                self.output_size = output_size if output_size is not None else kernel_size
            else:
                self.kernel_size = kernel_size
                self.stride = stride
                self.padding = padding
            self._kwargs = {k: v for k, v in kwargs.items()
                            if k not in ("name", "return_mask", "ceil_mode",
                                         "exclusive", "divisor_override",
                                         "data_format")}

        def forward(self, x):
            if self._adaptive:
                return getattr(F, fn_name)(x, self.output_size)
            return getattr(F, fn_name)(x, self.kernel_size, self.stride,
                                       self.padding)

    return _Pool


MaxPool1D = _pool_layer("max_pool1d")
MaxPool2D = _pool_layer("max_pool2d")
MaxPool3D = _pool_layer("max_pool3d")
AvgPool1D = _pool_layer("avg_pool1d")
AvgPool2D = _pool_layer("avg_pool2d")
AvgPool3D = _pool_layer("avg_pool3d")


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size)
