"""Layer: base class of all neural network modules.

Reference: `python/paddle/fluid/dygraph/layers.py` (Layer with parameter /
sublayer / buffer registries, forward hooks, state_dict, train/eval) and
`ParamBase` (`fluid/framework.py`).  The TPU-native addition is
``functional_state`` / ``load_functional_state``: a Layer's parameters and
buffers form a flat pytree so the whole module can be staged into a pure
jit-compiled function (see paddle_tpu.jit).
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ...core import dtype as dtype_mod
from ...core import framework
from ...core.tensor import Tensor


class Parameter(Tensor):
    """Trainable tensor (reference ParamBase, `fluid/framework.py`).

    ``mesh_axes`` is the TPU-native addition: an optional PartitionSpec-style
    tuple naming the mesh axis each dim is sharded over (e.g. ``(None,'mp')``
    for a column-parallel weight).  fleet's sharded train step reads it to
    build NamedShardings — replacing the reference's program-rewriting
    tensor-parallel optimizers.
    """

    __slots__ = ("optimize_attr", "is_bias", "mesh_axes")

    def __init__(self, data, dtype=None, name=None, is_bias=False):
        super().__init__(data, dtype=dtype, stop_gradient=False, name=name)
        self.trainable = True
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.is_bias = is_bias
        self.mesh_axes = None


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtype_mod.convert_dtype(dtype)
        self._parameters: Dict[str, Optional[Parameter]] = collections.OrderedDict()
        self._sub_layers: Dict[str, "Layer"] = collections.OrderedDict()
        self._buffers: Dict[str, Optional[Tensor]] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = 0
        self._name = name_scope or type(self).__name__.lower()

    # -- attribute routing (reference layers.py __setattr__) ---------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call super().__init__() first")
            params[name] = value
            for d in (layers, buffers):
                d is not None and d.pop(name, None)
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call super().__init__() first")
            layers[name] = value
            for d in (params, buffers):
                d is not None and d.pop(name, None)
            self.__dict__.pop(name, None)
        elif params is not None and name in params:
            if value is None:
                params[name] = None
            elif isinstance(value, Tensor):
                params[name].set_value(value)
            else:
                raise TypeError(f"cannot assign {type(value)} to parameter {name}")
        elif buffers is not None and name in buffers:
            if value is None or isinstance(value, Tensor):
                buffers[name] = value
            else:
                object.__setattr__(self, name, value)
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for d in ("_parameters", "_sub_layers", "_buffers"):
            reg = self.__dict__.get(d)
            if reg is not None and name in reg:
                return reg[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'"
        )

    def __delattr__(self, name):
        for d in (self._parameters, self._sub_layers, self._buffers):
            if name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + list(
            self._sub_layers
        ) + list(self._buffers)

    # -- construction helpers ----------------------------------------------
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ) -> Parameter:
        from .. import initializer as init

        dtype = dtype_mod.convert_dtype(dtype) if dtype else self._dtype
        if default_initializer is None:
            if is_bias:
                default_initializer = init.Constant(0.0)
            else:
                default_initializer = init.XavierUniform()
        # ParamAttr support (reference fluid/param_attr.py; str/initializer/
        # ParamAttr all accepted, False handled by callers as "no param")
        from ...framework.param_attr import ParamAttr

        attr = ParamAttr._to_attr(attr)
        if attr is False:
            raise ValueError("attr=False means no parameter; caller must "
                             "handle it before create_parameter")
        initializer = attr.initializer or default_initializer
        arr = initializer._init(shape, dtype)
        p = Parameter(arr, dtype=dtype, name=attr.name, is_bias=is_bias)
        if attr.regularizer is not None:
            p.regularizer = attr.regularizer
        if not attr.trainable:
            p.stop_gradient = True
            p.trainable = False
        if attr.learning_rate != 1.0:
            p.optimize_attr["learning_rate"] = attr.learning_rate
        return p

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- hooks --------------------------------------------------------------
    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # -- call ---------------------------------------------------------------
    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            out = hook(self, inputs, outputs)
            if out is not None:
                outputs = out
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def extra_repr(self):
        return ""

    def __repr__(self):
        lines = []
        for name, layer in self._sub_layers.items():
            mod_str = repr(layer)
            mod_str = "\n  ".join(mod_str.split("\n"))
            lines.append(f"({name}): {mod_str}")
        main = f"{type(self).__name__}({self.extra_repr()}"
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"

    # -- iteration ----------------------------------------------------------
    def named_sublayers(self, prefix="", include_self=False, layers_set=None) -> Iterator:
        if layers_set is None:
            layers_set = set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from layer.named_sublayers(
                prefix=sub_prefix, include_self=True, layers_set=layers_set
            )

    def sublayers(self, include_self=False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_parameters(self, prefix="", include_sublayers=True) -> Iterator:
        seen = set()
        for layer_prefix, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for name, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{layer_prefix}.{name}" if layer_prefix else name), p
            if not include_sublayers:
                break

    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True) -> Iterator:
        seen = set()
        for layer_prefix, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for name, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{layer_prefix}.{name}" if layer_prefix else name), b
            if not include_sublayers:
                break

    def buffers(self, include_sublayers=True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def children(self):
        return iter(self._sub_layers.values())

    def named_children(self):
        return iter(self._sub_layers.items())

    def apply(self, fn):
        for layer in self.children():
            layer.apply(fn)
        fn(self)
        return self

    # -- modes --------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix.rstrip(".")):
            dest[name] = p
        for name, b in self.named_buffers(prefix=structured_name_prefix.rstrip(".")):
            short = name.rsplit(".", 1)[-1]
            # find owning layer to check persistability
            dest[name] = b
        # drop non-persistable buffers
        for lp, layer in self.named_sublayers(include_self=True):
            for bname in layer._non_persistable_buffer_names:
                full = f"{lp}.{bname}" if lp else bname
                dest.pop(full, None)
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            arr = v.numpy() if isinstance(v, Tensor) else np.asarray(v)
            tgt = own[k]
            if list(arr.shape) != tgt.shape:
                raise ValueError(
                    f"shape mismatch for {k}: {list(arr.shape)} vs {tgt.shape}"
                )
            tgt.set_value(arr.astype(np.dtype(tgt.dtype.name) if hasattr(tgt.dtype, "name") else arr.dtype))
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dt = dtype_mod.convert_dtype(dtype)
            for p in self.parameters():
                p._array = p._array.astype(dt)
            for b in self.buffers():
                if dtype_mod.is_floating(b.dtype):
                    b._array = b._array.astype(dt)
        return self

    def float(self):
        return self.to(dtype="float32")

    def astype(self, dtype):
        return self.to(dtype=dtype)

    # -- functional staging (TPU-native; used by paddle_tpu.jit) -----------
    def functional_state(self) -> Tuple[Dict[str, Tensor], Dict[str, Tensor]]:
        params = {k: p for k, p in self.named_parameters()}
        buffers = {k: b for k, b in self.named_buffers()}
        return params, buffers

    def full_name(self):
        return self._name
