"""Norm layers (reference `python/paddle/nn/layer/norm.py`)."""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as init
from .layers import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=init.Constant(1.0),
            )
            self.bias = self.create_parameter(
                [num_features], attr=bias_attr, is_bias=True
            )
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features, jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features, jnp.float32)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats,
        )


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batchnorm (reference `sync_batch_norm_op.cu`).

    Under pjit/shard_map the mean/var reduction automatically spans the data
    axis because XLA sees the global batch; in eager single-process mode this
    is identical to BatchNorm.
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon, data_format=layer._data_format)
            if layer.weight is not None:
                out.weight.set_value(layer.weight)
                out.bias.set_value(layer.bias)
            out._mean.set_value(layer._mean)
            out._variance.set_value(layer._variance)
        for name, sub in layer._sub_layers.items():
            out._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr,
                default_initializer=init.Constant(1.0),
            )
            self.bias = self.create_parameter(
                self._normalized_shape, attr=bias_attr, is_bias=True
            )

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                [num_channels], attr=weight_attr,
                default_initializer=init.Constant(1.0),
            )
            self.bias = self.create_parameter(
                [num_channels], attr=bias_attr, is_bias=True
            )

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False:
            self.scale = None
            self.bias = None
        else:
            self.scale = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=init.Constant(1.0),
            )
            self.bias = self.create_parameter(
                [num_features], attr=bias_attr, is_bias=True
            )

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    """Spectral normalization of a weight (reference `spectral_norm_op.*`)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12, name=None):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        self.weight_u = self.create_parameter(
            [h], default_initializer=init.Normal(0, 1)
        )
        self.weight_v = self.create_parameter(
            [w], default_initializer=init.Normal(0, 1)
        )

    def forward(self, weight):
        from ...ops import matmul, reshape, transpose

        w = weight
        if self._dim != 0:
            perm = [self._dim] + [i for i in range(w.ndim) if i != self._dim]
            w = transpose(w, perm)
        h = w.shape[0]
        mat = reshape(w, [h, -1])
        u, v = self.weight_u, self.weight_v
        for _ in range(self._power_iters):
            v_new = matmul(mat, u, transpose_x=True)
            v = v_new / (v_new.norm() + self._eps)
            u_new = matmul(mat, v)
            u = u_new / (u_new.norm() + self._eps)
        sigma = (u * matmul(mat, v)).sum()
        out = w / sigma
        if self._dim != 0:
            inv = [0] * w.ndim
            perm = [self._dim] + [i for i in range(w.ndim) if i != self._dim]
            for i, p in enumerate(perm):
                inv[p] = i
            out = transpose(out, inv)
        return out
