"""paddle.Model high-level API.

Reference: `python/paddle/hapi/model.py:878` (Model.prepare/fit/evaluate/
predict/save/load, `fit` at :1523).  TPU-native: `prepare()` builds one
fused jit train step (`paddle_tpu.jit.TrainStep` — forward+backward+update
in a single donated XLA executable) instead of per-op dygraph dispatch.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor
from ..metric import Metric
from . import callbacks as cbks


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._loss = None
        self._optimizer = None
        self._metrics: List[Metric] = []
        self._train_step = None
        self.stop_training = False

    # -- setup --------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, Metric):
            self._metrics = [metrics]
        else:
            self._metrics = list(metrics)
        if optimizer is not None and optimizer._parameters is None:
            optimizer._parameters = self.network.parameters()
        self._train_step = None  # rebuilt lazily

    def _loss_fn(self, net, *batch):
        *xs, y = batch
        out = net(*xs)
        loss = self._loss(out, y)
        if isinstance(loss, (list, tuple)):
            from ..ops import add_n

            loss = add_n([l for l in loss])
        return loss

    def _ensure_train_step(self):
        if self._train_step is None:
            from ..jit import TrainStep

            self._train_step = TrainStep(self.network, self._loss_fn,
                                         self._optimizer)

    # -- imperative single-batch APIs ---------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        self._ensure_train_step()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        batch = list(inputs) + list(labels)
        loss = self._train_step(*batch)
        from ..optimizer.lr import LRScheduler

        return [float(loss.numpy())]

    def eval_batch(self, inputs, labels=None):
        from ..autograd import no_grad

        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        with no_grad():
            out = self.network(*inputs)
            loss = self._loss(out, labels[0]) if self._loss else None
        metrics = []
        for m in self._metrics:
            res = m.compute(out, labels[0])
            m.update(res)
            metrics.append(m.accumulate())
        return ([float(loss.numpy())] if loss is not None else []), metrics

    def predict_batch(self, inputs):
        from ..autograd import no_grad

        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        with no_grad():
            out = self.network(*inputs)
        return out

    # -- loops --------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        from ..io import DataLoader, Dataset

        if isinstance(train_data, Dataset):
            train_loader = DataLoader(train_data, batch_size=batch_size,
                                      shuffle=shuffle, drop_last=drop_last,
                                      num_workers=num_workers)
        else:
            train_loader = train_data
        if eval_data is not None and isinstance(eval_data, Dataset):
            eval_loader = DataLoader(eval_data, batch_size=batch_size,
                                     num_workers=num_workers)
        else:
            eval_loader = eval_data

        cb_list = [cbks.ProgBarLogger(log_freq, verbose=verbose)]
        cb_list.append(cbks.LRScheduler())
        if save_dir:
            cb_list.append(cbks.ModelCheckpoint(save_freq, save_dir))
        if callbacks:
            cb_list += list(callbacks)
        cbs = cbks.CallbackList(cb_list)
        cbs.set_model(self)
        try:
            steps = len(train_loader)
        except TypeError:
            steps = None
        cbs.set_params({"epochs": epochs, "steps": steps, "verbose": verbose})

        self.stop_training = False
        cbs.on_train_begin()
        it_count = 0
        for epoch in range(epochs):
            cbs.on_epoch_begin(epoch)
            logs = {}
            for step, batch in enumerate(train_loader):
                cbs.on_train_batch_begin(step)
                ins, labs = self._split_batch(batch)
                losses = self.train_batch(ins, labs)
                logs = {"loss": losses[0]}
                cbs.on_train_batch_end(step, logs)
                it_count += 1
                if num_iters is not None and it_count >= num_iters:
                    break
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0,
                                          num_workers=num_workers)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
                cbs.on_eval_end(logs={k.replace("eval_", ""): v
                                      for k, v in logs.items()})
            cbs.on_epoch_end(epoch, logs)
            if self.stop_training or (num_iters is not None and it_count >= num_iters):
                break
        cbs.on_train_end(logs)
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        from ..io import DataLoader, Dataset

        loader = DataLoader(eval_data, batch_size=batch_size,
                            num_workers=num_workers) \
            if isinstance(eval_data, Dataset) else eval_data
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            ins, labs = self._split_batch(batch)
            l, _ = self.eval_batch(ins, labs)
            if l:
                losses.append(l[0])
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            names = m.name()
            vals = m.accumulate()
            if isinstance(names, str):
                names, vals = [names], [vals]
            elif not isinstance(vals, (list, tuple)):
                vals = [vals]
            for n, v in zip(names, vals):
                logs[n] = v
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1):
        from ..io import DataLoader, Dataset

        loader = DataLoader(test_data, batch_size=batch_size,
                            num_workers=num_workers) \
            if isinstance(test_data, Dataset) else test_data
        outputs = []
        for batch in loader:
            ins = batch[0] if isinstance(batch, (list, tuple)) else batch
            out = self.predict_batch([ins])
            outputs.append(out.numpy() if isinstance(out, Tensor) else out)
        if stack_outputs:
            return [np.concatenate(outputs)]
        return [outputs]

    def _split_batch(self, batch):
        if isinstance(batch, (list, tuple)) and len(batch) >= 2:
            return list(batch[:-1]), [batch[-1]]
        return [batch], []

    # -- persistence --------------------------------------------------------
    def save(self, path, training=True):
        from ..framework.io import save as fsave

        if training:
            fsave(self.network.state_dict(), path + ".pdparams")
            if self._optimizer is not None:
                fsave(self._optimizer.state_dict(), path + ".pdopt")
        else:
            from .. import jit

            jit.save(self.network, path, input_spec=self._inputs)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as fload

        state = fload(path + ".pdparams")
        self.network.set_state_dict(state)
        import os

        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(fload(path + ".pdopt"))
        self._train_step = None

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .summary import summary

        return summary(self.network, input_size, dtype)
