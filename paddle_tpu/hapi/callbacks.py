"""hapi callbacks (reference `python/paddle/hapi/callbacks.py`)."""
from __future__ import annotations

import numbers
import time


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = callbacks

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)

            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._start = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            logs = logs or {}
            msg = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, numbers.Number) else f"{k}: {v}"
                for k, v in logs.items()
            )
            print(f"step {step}/{self.steps or '?'} - {msg}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._start
            logs = logs or {}
            msg = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, numbers.Number) else f"{k}: {v}"
                for k, v in logs.items()
            )
            print(f"Epoch {epoch + 1} done in {dt:.1f}s - {msg}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            import os

            self.model.save(os.path.join(self.save_dir, f"epoch_{epoch}"))

    def on_train_end(self, logs=None):
        if self.save_dir:
            import os

            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.wait = 0
        self.stopped_epoch = 0
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.better = lambda cur, best: cur > best + self.min_delta
            self.best = -float("inf")
        else:
            self.better = lambda cur, best: cur < best - self.min_delta
            self.best = float("inf")

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self.better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None) if opt else None
        from ..optimizer.lr import LRScheduler as Sched

        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if s and self.by_step:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if s and self.by_epoch:
            s.step()


class VisualDL(Callback):
    """Metric logging callback; writes JSONL (VisualDL itself is not
    available in this environment)."""

    def __init__(self, log_dir="./log"):
        super().__init__()
        self.log_dir = log_dir
        self._step = 0

    def on_train_batch_end(self, step, logs=None):
        import json
        import os

        os.makedirs(self.log_dir, exist_ok=True)
        with open(os.path.join(self.log_dir, "train.jsonl"), "a") as f:
            f.write(json.dumps({"step": step, **{k: float(v) if isinstance(v, numbers.Number) else str(v) for k, v in (logs or {}).items()}}) + "\n")
