from . import callbacks
from .model import Model
from .summary import summary
