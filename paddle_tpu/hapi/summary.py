"""Model summary (reference `python/paddle/hapi/model_summary.py`)."""
from __future__ import annotations

import numpy as np


def summary(net, input_size=None, dtypes=None):
    total_params = 0
    trainable_params = 0
    lines = ["-" * 64, f"{'Layer':<30}{'Param shape':<22}{'#':>10}", "=" * 64]
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total_params += n
        if p.trainable:
            trainable_params += n
        lines.append(f"{name:<30}{str(p.shape):<22}{n:>10}")
    lines += ["=" * 64,
              f"Total params: {total_params:,}",
              f"Trainable params: {trainable_params:,}",
              "-" * 64]
    print("\n".join(lines))
    return {"total_params": total_params, "trainable_params": trainable_params}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Per-layer FLOPs estimate (reference `python/paddle/hapi/
    dynamic_flops.py`): counts multiply-accumulates of Conv/Linear (x2 for
    FLOPs) plus norm/activation elementwise costs, via forward hooks on a
    dry run with zeros."""
    import paddle_tpu as paddle
    from .. import nn

    counts = {}
    hooks = []

    def out_shape(out):
        o = out[0] if isinstance(out, (list, tuple)) else out
        return tuple(o.shape)

    def hook(layer, inputs, output):
        name = type(layer).__name__
        f = 0
        if isinstance(layer, nn.Linear):
            # reference dynamic_flops count_linear: in_features * out.numel
            # (MACs, no factor 2)
            in_features = int(layer.weight.shape[0])
            f = in_features * int(np.prod(out_shape(output)))
        elif hasattr(layer, "weight") and getattr(layer, "_stride", None) \
                is not None and layer.weight is not None:
            # reference count_convNd: out.numel * (Cin/g * prod(k) + bias).
            # Cin comes from the layer when known (transposed convs store
            # weights as [Cin, Cout/g, *k]); a duck-typed layer's
            # w.shape[1] is ALREADY Cin/g — don't divide again.
            w = layer.weight
            o = out_shape(output)
            if hasattr(layer, "_in_channels"):
                cin_g = int(layer._in_channels) // max(
                    int(getattr(layer, "_groups", 1)), 1)
            else:
                cin_g = int(w.shape[1])
            k_elems = int(np.prod(w.shape[2:]))
            bias_ops = 1 if getattr(layer, "bias", None) is not None else 0
            f = int(np.prod(o)) * (cin_g * k_elems + bias_ops)
        elif isinstance(layer, (nn.BatchNorm, nn.BatchNorm1D, nn.BatchNorm2D,
                                nn.BatchNorm3D, nn.LayerNorm)):
            f = 2 * int(np.prod(out_shape(output)))
        if custom_ops and type(layer) in custom_ops:
            f = custom_ops[type(layer)](layer, inputs, output)
        if f:
            # accumulate: weight-shared layers may run several times per
            # forward (reference dynamic_flops does m.total_ops += ...)
            prev = counts.get(id(layer), (name, 0))[1]
            counts[id(layer)] = (name, prev + f)

    for sub in net.sublayers(include_self=True):
        hooks.append(sub.register_forward_post_hook(hook))
    was_training = net.training
    net.eval()
    try:
        with paddle.no_grad():
            x = paddle.zeros(list(input_size), dtype="float32")
            net(x)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()
    total = sum(f for _, f in counts.values())
    if print_detail:
        for name, f in counts.values():
            print(f"{name:<24}{f:>16,}")
    print(f"Total Flops: {total:,}")
    return total
