"""Model summary (reference `python/paddle/hapi/model_summary.py`)."""
from __future__ import annotations

import numpy as np


def summary(net, input_size=None, dtypes=None):
    total_params = 0
    trainable_params = 0
    lines = ["-" * 64, f"{'Layer':<30}{'Param shape':<22}{'#':>10}", "=" * 64]
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total_params += n
        if p.trainable:
            trainable_params += n
        lines.append(f"{name:<30}{str(p.shape):<22}{n:>10}")
    lines += ["=" * 64,
              f"Total params: {total_params:,}",
              f"Trainable params: {trainable_params:,}",
              "-" * 64]
    print("\n".join(lines))
    return {"total_params": total_params, "trainable_params": trainable_params}
