"""`paddle.incubate` equivalents (experimental surface)."""
from . import checkpoint  # noqa: F401
