"""Auto-checkpoint: preemption-safe epoch-range training.

Reference: `python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py:71`
— `train_epoch_range(max_epoch_num)` yields epoch numbers while
transparently checkpointing executor state to HDFS keyed by job id
(env `PADDLE_EDL_HDFS_*` `:90-107`) and resuming after preemption.

TPU-native: checkpoints are local/NFS directory files (orbax-style per-step
dirs would also work; the reference's HDFS client is an env detail, not a
capability).  State captured = registered Layers'/Optimizers' state_dicts +
the RNG seed + epoch counter.  Resume: the next `train_epoch_range` with
the same job id skips completed epochs and restores the latest state.

Env (mirroring the reference's knobs):
  PADDLE_RUNNING_ENV=PADDLE_EDL_AUTO_CHECKPOINT  enable
  PADDLE_JOB_ID                                  job identity
  PADDLE_EDL_CHECKPOINT_DIR                      storage dir (replaces HDFS)
  PADDLE_EDL_SAVE_CHECKPOINT_INTER               min seconds between saves
"""
from __future__ import annotations

import json
import os
import time
from typing import Optional

__all__ = ["train_epoch_range", "register", "unregister", "_reset"]

_registered = {"models": [], "optimizers": []}


def _enabled() -> bool:
    return os.environ.get("PADDLE_RUNNING_ENV") == \
        "PADDLE_EDL_AUTO_CHECKPOINT"


def _job_dir(checkpoint_dir: Optional[str]) -> str:
    base = checkpoint_dir or os.environ.get("PADDLE_EDL_CHECKPOINT_DIR",
                                            "./auto_checkpoint")
    job = os.environ.get("PADDLE_JOB_ID", "default_job")
    return os.path.join(base, job)


def register(model=None, optimizer=None):
    """Attach objects whose state_dicts travel with the checkpoint
    (the reference hooks the Executor; dygraph state is explicit)."""
    if model is not None:
        _registered["models"].append(model)
    if optimizer is not None:
        _registered["optimizers"].append(optimizer)


def unregister():
    _registered["models"].clear()
    _registered["optimizers"].clear()


def _reset():
    unregister()


def _save(job_dir: str, epoch: int):
    from ... import framework

    os.makedirs(job_dir, exist_ok=True)
    payload = {}
    for i, m in enumerate(_registered["models"]):
        payload[f"model_{i}"] = m.state_dict()
    for i, o in enumerate(_registered["optimizers"]):
        payload[f"opt_{i}"] = o.state_dict()
    # atomic: state first (tmp+rename), meta last — a preemption mid-save
    # leaves the previous consistent (state, meta) pair intact
    state_path = os.path.join(job_dir, "state.pdparams")
    framework.io.save(payload, state_path + ".tmp")
    os.replace(state_path + ".tmp", state_path)
    meta = {"epoch_no": epoch, "timestamp": time.time()}
    tmp = os.path.join(job_dir, "meta.json.tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, os.path.join(job_dir, "meta.json"))


def _load_meta(job_dir: str) -> Optional[dict]:
    path = os.path.join(job_dir, "meta.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def _restore(job_dir: str):
    from ... import framework

    path = os.path.join(job_dir, "state.pdparams")
    if not os.path.exists(path):
        return
    payload = framework.io.load(path)
    for i, m in enumerate(_registered["models"]):
        key = f"model_{i}"
        if key in payload:
            m.set_state_dict(payload[key])
    for i, o in enumerate(_registered["optimizers"]):
        key = f"opt_{i}"
        if key in payload and hasattr(o, "set_state_dict"):
            o.set_state_dict(payload[key])


def train_epoch_range(max_epoch_num: int, save_checkpoint_inter=None,
                      checkpoint_dir: Optional[str] = None):
    """Generator of epoch numbers with transparent checkpoint/resume
    (reference `acp._get_train_epoch_range()._run(...)` loop).  When
    auto-checkpoint is disabled it degrades to plain `range`."""
    if not _enabled():
        for epoch in range(max_epoch_num):
            yield epoch
        return

    job_dir = _job_dir(checkpoint_dir)
    inter = save_checkpoint_inter
    if inter is None:
        inter = float(os.environ.get("PADDLE_EDL_SAVE_CHECKPOINT_INTER",
                                     "0"))
    meta = _load_meta(job_dir)
    start = 0
    if meta is not None:
        start = int(meta["epoch_no"]) + 1
        _restore(job_dir)
    last_save = 0.0
    for epoch in range(start, max_epoch_num):
        yield epoch
        now = time.monotonic()
        if now - last_save >= inter:
            _save(job_dir, epoch)
            last_save = now
    # final epoch state always persisted
    if start < max_epoch_num:
        _save(job_dir, max_epoch_num - 1)
