"""Optimizers.

Reference: `python/paddle/optimizer/` + CUDA update kernels
`operators/optimizers/` (sgd, momentum, adam w/ multi-precision master
weights, adamw, adagrad, adadelta, adamax, rmsprop, lamb, lars).
Each optimizer here is a pure functional update rule (see optimizer.py base);
master-weight AMP semantics are achieved by keeping params fp32 and casting
to bf16 inside the jit'd forward (the XLA-native version of the reference's
`multi_precision` adam kernels).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from . import lr
from .optimizer import Optimizer


class SGD(Optimizer):
    """reference `operators/optimizers/sgd_op.cc`."""

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)

    def _update_param(self, p, g, slot, lr, step):
        return p - lr * g, slot

    def _append_static_update(self, block, param, grad, lr_name):
        block.append_op("sgd",
                        {"Param": param.name, "Grad": grad.name,
                         "LearningRate": lr_name},
                        {"ParamOut": param.name}, {})


class Momentum(Optimizer):
    """reference `operators/optimizers/momentum_op.h` (use_nesterov attr)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None, multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _init_slot(self, p):
        return {"velocity": jnp.zeros_like(p)}

    def _update_param(self, p, g, slot, lr, step):
        v = self._momentum * slot["velocity"] + g
        if self._use_nesterov:
            new_p = p - lr * (g + self._momentum * v)
        else:
            new_p = p - lr * v
        return new_p, {"velocity": v}

    def _append_static_update(self, block, param, grad, lr_name):
        vname = param.name + "_velocity_0"
        block.create_var(vname, param.shape, "float32", persistable=True)
        block.append_op(
            "momentum",
            {"Param": param.name, "Grad": grad.name, "Velocity": vname,
             "LearningRate": lr_name},
            {"ParamOut": param.name, "VelocityOut": vname},
            {"mu": float(self._momentum),
             "use_nesterov": bool(self._use_nesterov)})


class Adam(Optimizer):
    """reference `operators/optimizers/adam_op.h` (incl. beta pow accumulators)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _init_slot(self, p):
        return {
            "moment1": jnp.zeros_like(p),
            "moment2": jnp.zeros_like(p),
            "beta1_pow": jnp.ones((), jnp.float32),
            "beta2_pow": jnp.ones((), jnp.float32),
        }

    def _update_param(self, p, g, slot, lr, step):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * slot["moment1"] + (1 - b1) * g
        v = b2 * slot["moment2"] + (1 - b2) * g * g
        b1p = slot["beta1_pow"] * b1
        b2p = slot["beta2_pow"] * b2
        lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
        denom = jnp.sqrt(v) + eps * jnp.sqrt(1 - b2p)
        new_p = p - (lr_t * (m / denom)).astype(p.dtype)
        return new_p, {"moment1": m, "moment2": v, "beta1_pow": b1p,
                       "beta2_pow": b2p}


class AdamW(Adam):
    """Decoupled weight decay (reference python wrapper `optimizer/adamw.py`
    over adam op with coeff)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         name)
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decoupled_weight_decay(self):
        return True

    def _update_param(self, p, g, slot, lr, step):
        new_p, new_slot = super()._update_param(p, g, slot, lr, step)
        if self._weight_decay:
            new_p = new_p - (lr * self._weight_decay * p).astype(p.dtype)
        return new_p, new_slot


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-06, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _init_slot(self, p):
        return {"moment": jnp.full_like(p, self._init_acc)}

    def _update_param(self, p, g, slot, lr, step):
        m = slot["moment"] + g * g
        return p - lr * g / (jnp.sqrt(m) + self._epsilon), {"moment": m}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-06, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._rho = rho

    def _init_slot(self, p):
        return {"avg_squared_grad": jnp.zeros_like(p),
                "avg_squared_update": jnp.zeros_like(p)}

    def _update_param(self, p, g, slot, lr, step):
        rho, eps = self._rho, self._epsilon
        asg = rho * slot["avg_squared_grad"] + (1 - rho) * g * g
        update = g * jnp.sqrt(slot["avg_squared_update"] + eps) / jnp.sqrt(asg + eps)
        asu = rho * slot["avg_squared_update"] + (1 - rho) * update * update
        return p - lr * update, {"avg_squared_grad": asg,
                                 "avg_squared_update": asu}


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _init_slot(self, p):
        return {"moment": jnp.zeros_like(p), "inf_norm": jnp.zeros_like(p),
                "beta1_pow": jnp.ones((), jnp.float32)}

    def _update_param(self, p, g, slot, lr, step):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * slot["moment"] + (1 - b1) * g
        u = jnp.maximum(b2 * slot["inf_norm"], jnp.abs(g))
        b1p = slot["beta1_pow"] * b1
        new_p = p - (lr / (1 - b1p)) * m / (u + eps)
        return new_p, {"moment": m, "inf_norm": u, "beta1_pow": b1p}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-06, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_slot(self, p):
        return {"mean_square": jnp.zeros_like(p),
                "mean_grad": jnp.zeros_like(p),
                "momentum": jnp.zeros_like(p)}

    def _update_param(self, p, g, slot, lr, step):
        rho, eps = self._rho, self._epsilon
        ms = rho * slot["mean_square"] + (1 - rho) * g * g
        mg = rho * slot["mean_grad"] + (1 - rho) * g if self._centered else slot["mean_grad"]
        denom = ms - mg * mg if self._centered else ms
        mom = self._momentum * slot["momentum"] + lr * g / jnp.sqrt(denom + eps)
        return p - mom, {"mean_square": ms, "mean_grad": mg, "momentum": mom}


class Lamb(Optimizer):
    """reference `operators/optimizers/lamb_op.h`."""

    _elementwise_update = False  # trust ratio uses per-param norms

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-06, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_slot(self, p):
        return {"moment1": jnp.zeros_like(p), "moment2": jnp.zeros_like(p),
                "beta1_pow": jnp.ones((), jnp.float32),
                "beta2_pow": jnp.ones((), jnp.float32)}

    def _update_param(self, p, g, slot, lr, step):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * slot["moment1"] + (1 - b1) * g
        v = b2 * slot["moment2"] + (1 - b2) * g * g
        b1p = slot["beta1_pow"] * b1
        b2p = slot["beta2_pow"] * b2
        m_hat = m / (1 - b1p)
        v_hat = v / (1 - b2p)
        r = m_hat / (jnp.sqrt(v_hat) + eps) + self._lamb_wd * p
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p.astype(jnp.float32))))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r.astype(jnp.float32))))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - lr * trust * r, {"moment1": m, "moment2": v,
                                    "beta1_pow": b1p, "beta2_pow": b2p}


class Lars(Momentum):
    """reference `operators/optimizers/lars_momentum_op.*`."""

    _elementwise_update = False  # local lr uses per-param norms

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 lars_coeff=0.001, lars_weight_decay=0.0005, epsilon=1e-9,
                 grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, momentum, parameters, False, None,
                         grad_clip, name)
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._lars_eps = epsilon

    def _update_param(self, p, g, slot, lr, step):
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p.astype(jnp.float32))))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self._lars_coeff * w_norm / (g_norm + self._lars_wd * w_norm + self._lars_eps),
            1.0,
        )
        eff_g = g + self._lars_wd * p
        v = self._momentum * slot["velocity"] + lr * local_lr * eff_g
        return p - v, {"velocity": v}


class Ftrl(Optimizer):
    """reference `operators/optimizers/ftrl_op.h` (FTRL-proximal):
    squared-accum + linear-accum update with L1/L2 shrinkage."""

    def __init__(self, learning_rate=0.001, l1=0.0, l2=0.0, lr_power=-0.5,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._l1 = float(l1)
        self._l2 = float(l2)
        self._lr_power = float(lr_power)

    def _init_slot(self, p):
        return {"squared": jnp.zeros_like(p), "linear": jnp.zeros_like(p)}

    def _update_param(self, p, g, slot, lr, step):
        sq, lin = slot["squared"], slot["linear"]
        new_sq = sq + g * g
        lp = -self._lr_power
        sigma = (new_sq ** lp - sq ** lp) / lr
        new_lin = lin + g - sigma * p
        pre = jnp.where(jnp.abs(new_lin) > self._l1,
                        (self._l1 * jnp.sign(new_lin) - new_lin)
                        / (new_sq ** lp / lr + 2 * self._l2),
                        0.0)
        return pre.astype(p.dtype), {"squared": new_sq, "linear": new_lin}


class Dpsgd(Optimizer):
    """reference `operators/optimizers/dpsgd_op.h` (differentially
    private SGD): per-step gradient clipping to `clip` + gaussian noise
    scaled by `sigma`, then a plain SGD step."""

    _elementwise_update = False  # per-param grad-norm clip + noise

    def __init__(self, learning_rate=0.001, clip=10.0, batch_size=16.0,
                 sigma=1.0, parameters=None, seed=0, name=None, **kw):
        super().__init__(learning_rate, parameters)
        self._dp_clip = float(clip)
        self._batch = float(batch_size)
        self._sigma = float(sigma)
        self._seed = int(seed)
        self._param_ctr = 0
        self._last_step = None

    def _init_slot(self, p):
        return {}

    def _update_param(self, p, g, slot, lr, step):
        norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
        scale = jnp.minimum(1.0, self._dp_clip / jnp.maximum(norm, 1e-12))
        # per-parameter independent noise: fold in a per-step param index
        # (stable under tracing — the counter advances at trace time, once
        # per parameter position) in addition to (seed, step)
        if self._last_step is not step:
            self._last_step = step
            self._param_ctr = 0
        self._param_ctr += 1
        key = jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(self._seed),
                               jnp.asarray(step, jnp.int32)),
            self._param_ctr)
        noise = jax.random.normal(key, g.shape, jnp.float32) * (
            self._dp_clip * self._sigma / self._batch)
        gg = g * scale + noise.astype(g.dtype)
        return p - (lr * gg).astype(p.dtype), {}


__all__ = ["Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adagrad",
           "Adadelta", "Adamax", "RMSProp", "Lamb", "Lars", "Ftrl",
           "Dpsgd", "lr"]


# ---------------------------------------------------------------------------
# static-graph update-op lowerings (reference `fluid/optimizer.py`
# _append_optimize_op per class): minimize() emits the same in-program
# optimizer ops a reference training program carries, so exported
# training programs round-trip through static.Executor.  Slot vars are
# created persistable; the interp's optimizer translators default them
# (zeros / beta powers) on first step.
# ---------------------------------------------------------------------------
def _mk_slot_var(block, param, suffix):
    name = f"{param.name}_{suffix}"
    block.create_var(name, param.shape, "float32", persistable=True)
    return name


def _adam_static_update(self, block, param, grad, lr_name):
    m1 = _mk_slot_var(block, param, "moment1_0")
    m2 = _mk_slot_var(block, param, "moment2_0")
    b1p = f"{param.name}_beta1_pow_acc_0"
    b2p = f"{param.name}_beta2_pow_acc_0"
    block.create_var(b1p, [1], "float32", persistable=True)
    block.create_var(b2p, [1], "float32", persistable=True)
    attrs = {"beta1": float(self._beta1), "beta2": float(self._beta2),
             "epsilon": float(self._epsilon)}
    optype = "adam"
    if getattr(self, "_decoupled_weight_decay", None) and \
            self._decoupled_weight_decay():
        optype = "adamw"
        wd = self._weight_decay
        attrs["coeff"] = float(wd if isinstance(wd, (int, float))
                               else 0.01)
        attrs["with_decay"] = True
    block.append_op(
        optype,
        {"Param": param.name, "Grad": grad.name, "LearningRate": lr_name,
         "Moment1": m1, "Moment2": m2, "Beta1Pow": b1p, "Beta2Pow": b2p},
        {"ParamOut": param.name, "Moment1Out": m1, "Moment2Out": m2,
         "Beta1PowOut": b1p, "Beta2PowOut": b2p}, attrs)


Adam._append_static_update = _adam_static_update


def _adagrad_static_update(self, block, param, grad, lr_name):
    mom = _mk_slot_var(block, param, "moment_0")
    block.append_op(
        "adagrad",
        {"Param": param.name, "Grad": grad.name, "Moment": mom,
         "LearningRate": lr_name},
        {"ParamOut": param.name, "MomentOut": mom},
        {"epsilon": float(self._epsilon)})


Adagrad._append_static_update = _adagrad_static_update


def _adadelta_static_update(self, block, param, grad, lr_name):
    asg = _mk_slot_var(block, param, "avg_squared_grad_0")
    asu = _mk_slot_var(block, param, "avg_squared_update_0")
    block.append_op(
        "adadelta",
        {"Param": param.name, "Grad": grad.name, "AvgSquaredGrad": asg,
         "AvgSquaredUpdate": asu},
        {"ParamOut": param.name, "AvgSquaredGradOut": asg,
         "AvgSquaredUpdateOut": asu},
        {"rho": float(self._rho), "epsilon": float(self._epsilon)})


Adadelta._append_static_update = _adadelta_static_update


def _adamax_static_update(self, block, param, grad, lr_name):
    mom = _mk_slot_var(block, param, "moment_0")
    inf = _mk_slot_var(block, param, "inf_norm_0")
    b1p = f"{param.name}_beta1_pow_acc_0"
    block.create_var(b1p, [1], "float32", persistable=True)
    block.append_op(
        "adamax",
        {"Param": param.name, "Grad": grad.name, "LearningRate": lr_name,
         "Moment": mom, "InfNorm": inf, "Beta1Pow": b1p},
        {"ParamOut": param.name, "MomentOut": mom, "InfNormOut": inf,
         "Beta1PowOut": b1p},
        {"beta1": float(self._beta1), "beta2": float(self._beta2),
         "epsilon": float(self._epsilon)})


Adamax._append_static_update = _adamax_static_update


def _rmsprop_static_update(self, block, param, grad, lr_name):
    ms = _mk_slot_var(block, param, "mean_square_0")
    mg = _mk_slot_var(block, param, "mean_grad_0")
    mom = _mk_slot_var(block, param, "momentum_0")
    block.append_op(
        "rmsprop",
        {"Param": param.name, "Grad": grad.name, "LearningRate": lr_name,
         "MeanSquare": ms, "MeanGrad": mg, "Moment": mom},
        {"ParamOut": param.name, "MeanSquareOut": ms,
         "MeanGradOut": mg, "MomentOut": mom},
        {"decay": float(self._rho), "epsilon": float(self._epsilon),
         "momentum": float(self._momentum),
         "centered": bool(self._centered)})


RMSProp._append_static_update = _rmsprop_static_update


def _lamb_static_update(self, block, param, grad, lr_name):
    m1 = _mk_slot_var(block, param, "moment1_0")
    m2 = _mk_slot_var(block, param, "moment2_0")
    b1p = f"{param.name}_beta1_pow_acc_0"
    b2p = f"{param.name}_beta2_pow_acc_0"
    block.create_var(b1p, [1], "float32", persistable=True)
    block.create_var(b2p, [1], "float32", persistable=True)
    block.append_op(
        "lamb",
        {"Param": param.name, "Grad": grad.name, "LearningRate": lr_name,
         "Moment1": m1, "Moment2": m2, "Beta1Pow": b1p, "Beta2Pow": b2p},
        {"ParamOut": param.name, "Moment1Out": m1, "Moment2Out": m2,
         "Beta1PowOut": b1p, "Beta2PowOut": b2p},
        {"beta1": float(self._beta1), "beta2": float(self._beta2),
         "epsilon": float(self._epsilon),
         "weight_decay": float(self._lamb_wd)})


Lamb._append_static_update = _lamb_static_update
