"""Optimizer base.

Reference: `python/paddle/optimizer/optimizer.py` (accumulator management,
regularization, grad clip, lr scheduling) over per-param CUDA update ops
(`operators/optimizers/`).  TPU-native: every optimizer defines a pure
functional core ``_update_param(p, g, state, lr) -> (new_p, new_state)``
usable in two modes:

* imperative ``step()`` — applies the core eagerly per parameter (XLA caches
  the tiny update executable per shape);
* staged — ``apply_gradients(params, grads, state, lr)`` maps the core over a
  whole param pytree inside a jit'd train step (used by paddle_tpu.jit and
  fleet), where XLA fuses all updates into one fused kernel sweep — the moral
  equivalent of the reference's fused coalesce_tensor + single kernel path.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp

from ..core import framework
from ..core.tensor import Tensor
from .lr import LRScheduler


def collect_lr_mults(params: Dict[str, object]) -> Optional[Dict[str, float]]:
    """ParamAttr.learning_rate multipliers for a named param dict (reference
    ``_create_param_lr``); None when every multiplier is 1.0 so callers can
    skip the per-param scaling entirely."""
    mults = {
        k: float((getattr(t, "optimize_attr", None) or {})
                 .get("learning_rate", 1.0))
        for k, t in params.items()
    }
    return None if all(m == 1.0 for m in mults.values()) else mults


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        self._lr = learning_rate
        self._parameters = list(parameters) if parameters is not None else None
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        if weight_decay is None:
            self._weight_decay = 0.0
        elif isinstance(weight_decay, (int, float)):
            self._weight_decay = float(weight_decay)
        else:  # L2Decay object
            self._weight_decay = float(getattr(weight_decay, "_coeff",
                                               getattr(weight_decay, "coeff", 0.0)))
        # per-parameter slot state keyed by id(param)
        self._state: Dict[int, dict] = {}
        self._step_count = 0

    # -- learning rate ------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return float(self._lr())
        return float(self._lr)

    def set_lr(self, value):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._lr = float(value)

    @property
    def _learning_rate(self):
        return self._lr

    # Updates that are strictly elementwise in (p, g, slot) may be packed
    # across parameters (the pipeline step's fused packed-vector update);
    # optimizers using per-parameter norms (Lamb, Lars, Dpsgd trust/clip
    # ratios) must opt out so callers fall back to per-param updates.
    _elementwise_update = True

    # -- functional core (override) ----------------------------------------
    def _init_slot(self, param_array) -> dict:
        return {}

    def _update_param(self, p, g, slot, lr, step):
        raise NotImplementedError

    # -- decay/clip helpers --------------------------------------------------
    def _decoupled_weight_decay(self) -> bool:
        return False

    def _apply_decay(self, p, g, param_obj=None):
        """Regularization folded into the gradient (reference
        `regularizer.py` appends scaled param to grad).  A per-parameter
        regularizer on ParamAttr overrides the optimizer-level decay
        (reference `optimizer.py` `_create_regularization_of_grad`)."""
        reg = getattr(param_obj, "regularizer", None) if param_obj is not None else None
        if reg is not None:
            return g + reg(p)
        if self._weight_decay and not self._decoupled_weight_decay():
            return g + self._weight_decay * p
        return g

    # -- imperative step ----------------------------------------------------
    def step(self):
        params = self._parameters
        if params is None:
            raise ValueError("optimizer constructed without parameters")
        lr = self.get_lr()
        self._step_count += 1
        with framework.no_grad_guard():
            pgs = [(p, p.grad) for p in params
                   if p.grad is not None and p.trainable]
            if self._grad_clip is not None:
                pgs = self._grad_clip(pgs)
            for p, g in pgs:
                if g is None:
                    continue
                key = id(p)
                if key not in self._state:
                    self._state[key] = self._init_slot(p._array)
                garr = self._apply_decay(p._array,
                                         g._array.astype(p._array.dtype), p)
                # per-parameter LR multiplier from ParamAttr.learning_rate
                # (reference optimizer.py _create_param_lr)
                oattr = getattr(p, "optimize_attr", None) or {}
                mult = float(oattr.get("learning_rate", 1.0))
                new_p, new_slot = self._update_param(
                    p._array, garr, self._state[key],
                    lr * mult if mult != 1.0 else lr, self._step_count
                )
                p._array = new_p
                self._state[key] = new_slot

    minimize = None  # assigned below

    def clear_grad(self, set_to_zero=False):
        params = self._parameters or []
        for p in params:
            p.grad = None

    clear_gradients = clear_grad

    # -- staged/pytree form (used under jit; pure) ---------------------------
    def init_state(self, params: dict):
        import jax

        return {
            k: self._init_slot(v._array if isinstance(v, Tensor) else v)
            for k, v in params.items()
        }

    def apply_gradients(self, params: dict, grads: dict, state: dict, lr,
                        step=1, lr_mults: Optional[dict] = None):
        """Pure pytree update: params/grads dict[str]->array.

        ``lr_mults`` carries per-parameter ParamAttr.learning_rate
        multipliers (reference ``_create_param_lr``) keyed like params.
        """
        new_params, new_state = {}, {}
        if self._grad_clip is not None:
            keys = [k for k in params if grads.get(k) is not None]
            clipped = self._grad_clip.clip_arrays([grads[k] for k in keys])
            grads = dict(grads)
            for k, c in zip(keys, clipped):
                grads[k] = c
        for k, p in params.items():
            g = grads.get(k)
            if g is None:
                new_params[k] = p
                new_state[k] = state.get(k, {})
                continue
            g = self._apply_decay(p, g.astype(p.dtype))
            mult = lr_mults.get(k, 1.0) if lr_mults else 1.0
            np_, ns_ = self._update_param(
                p, g, state.get(k) or self._init_slot(p),
                lr * mult if mult != 1.0 else lr, step)
            new_params[k] = np_
            new_state[k] = ns_
        return new_params, new_state

    # -- checkpointing -------------------------------------------------------
    def state_dict(self):
        out = {"step": self._step_count}
        if isinstance(self._lr, LRScheduler):
            out["LR_Scheduler"] = self._lr.state_dict()
        if self._parameters is not None:
            for i, p in enumerate(self._parameters):
                slot = self._state.get(id(p))
                if slot:
                    for sk, sv in slot.items():
                        out[f"param{i}.{sk}"] = Tensor(sv) if not isinstance(sv, Tensor) else sv
        return out

    def set_state_dict(self, state):
        self._step_count = state.get("step", 0)
        if isinstance(self._lr, LRScheduler) and "LR_Scheduler" in state:
            self._lr.set_state_dict(state["LR_Scheduler"])
        if self._parameters is not None:
            for i, p in enumerate(self._parameters):
                slot = {}
                prefix = f"param{i}."
                for k, v in state.items():
                    if isinstance(k, str) and k.startswith(prefix):
                        arr = v._array if isinstance(v, Tensor) else jnp.asarray(v)
                        slot[k[len(prefix):]] = arr
                if slot:
                    self._state[id(p)] = slot


def _minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
    from ..static.program import Variable as _StaticVar

    if isinstance(loss, _StaticVar):
        return _minimize_static(self, loss, parameters, no_grad_set)
    loss.backward()
    self.step()
    self.clear_grad()
    return None, [(p, p.grad) for p in (self._parameters or [])]


_STATIC_LR_COUNTER = [0]


def _minimize_static(self, loss, parameters=None, no_grad_set=None):
    """Static-graph minimize (reference `fluid/optimizer.py` minimize):
    append_backward + optimizer ops into the loss's program, so
    Executor.run becomes a real training step (the Executor writes
    updated persistable vars back into its scope)."""
    from ..static import append_backward

    block = loss.block
    pairs = append_backward(loss, parameter_list=parameters,
                            no_grad_set=no_grad_set)
    _STATIC_LR_COUNTER[0] += 1
    lr_name = f"learning_rate_{_STATIC_LR_COUNTER[0]}"
    block.create_var(lr_name, [1], "float32", persistable=True)
    block.append_op("fill_constant", {}, {"Out": lr_name},
                    {"shape": [1], "dtype": 5,
                     "value": float(self.get_lr())})
    # remember the fill op so set_lr can rewrite it (and bump the program
    # version, which is part of the Executor's compile-cache key)
    if not hasattr(self, "_static_lr_sites"):
        self._static_lr_sites = []
    self._static_lr_sites.append(
        (block.program, block.desc["ops"][-1]))
    for p, g in pairs:
        self._append_static_update(block, p, g, lr_name)
    return None, pairs


def _append_static_update(self, block, param, grad, lr_name):
    """Emit this optimizer's update op (override per subclass; reference
    `optimizer.py _append_optimize_op`)."""
    raise NotImplementedError(
        f"{type(self).__name__} has no static-graph update op lowering "
        "yet; use SGD or Momentum for static minimize()")


_orig_set_lr = Optimizer.set_lr


def _set_lr(self, value):
    _orig_set_lr(self, value)
    # propagate into any static programs this optimizer minimized
    for program, fill_desc in getattr(self, "_static_lr_sites", ()):
        for a in fill_desc.get("attrs", []):
            if a.get("name") == "value":
                a["f"] = float(value)
        program.desc["version"]["version"] = \
            program.desc["version"].get("version", 0) + 1


Optimizer.minimize = _minimize
Optimizer._append_static_update = _append_static_update
Optimizer.set_lr = _set_lr
