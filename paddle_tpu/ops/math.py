"""Math ops (elementwise + reductions + matmul family).

Reference: `paddle/fluid/operators/elementwise/` (10.6k LoC of CUDA broadcast
kernels), `operators/reduce_ops/`, `operators/matmul_v2_op.*`,
`operators/activation_op.cc` — all collapse to jnp/lax calls that XLA fuses
and tiles onto the VPU/MXU; Python API `python/paddle/tensor/math.py`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ..core.dispatch import BLACK, WHITE, dispatch
from ..core.tensor import Tensor, unwrap


def _ew(jfn):
    def op(x, name=None):
        return dispatch(jfn, x)

    return op


def _binary(jfn):
    def op(x, y, name=None):
        return dispatch(jfn, x, y)

    return op


# -- elementwise binary -----------------------------------------------------
add = _binary(jnp.add)
subtract = _binary(jnp.subtract)
multiply = _binary(jnp.multiply)
divide = _binary(jnp.divide)
floor_divide = _binary(jnp.floor_divide)
remainder = _binary(jnp.remainder)
mod = remainder
floor_mod = remainder
pow = _binary(jnp.power)
maximum = _binary(jnp.maximum)
minimum = _binary(jnp.minimum)
fmax = _binary(jnp.fmax)
fmin = _binary(jnp.fmin)
atan2 = _binary(jnp.arctan2)
hypot = _binary(jnp.hypot)
logaddexp = _binary(jnp.logaddexp)
heaviside = _binary(jnp.heaviside)
gcd = _binary(jnp.gcd)
lcm = _binary(jnp.lcm)
nextafter = _binary(jnp.nextafter)
copysign = _binary(jnp.copysign)
ldexp = _binary(lambda x, y: jnp.ldexp(x, y.astype(jnp.int32)))
inner = _binary(jnp.inner)
outer = _binary(jnp.outer)
kron = _binary(jnp.kron)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s, b = unwrap(scale), unwrap(bias)

    def f(a):
        out = a * s + b if bias_after_scale else (a + b) * s
        return out

    out = dispatch(f, x)
    if act is not None:
        from ..nn import functional as F

        out = getattr(F, act)(out)
    return out


def add_n(inputs, name=None):
    import functools

    if isinstance(inputs, Tensor):
        return inputs
    return dispatch(lambda *xs: functools.reduce(jnp.add, xs), *inputs)


def lerp(x, y, weight, name=None):
    if not isinstance(weight, Tensor):
        return dispatch(lambda a, b: a + weight * (b - a), x, y)
    return dispatch(lambda a, b, w: a + w * (b - a), x, y, weight)


# -- elementwise unary ------------------------------------------------------
abs = _ew(jnp.abs)
sqrt = _ew(jnp.sqrt)
rsqrt = _ew(lambda a: jax.lax.rsqrt(a))
square = _ew(jnp.square)
exp = _ew(jnp.exp)
expm1 = _ew(jnp.expm1)
log = _ew(jnp.log)
log2 = _ew(jnp.log2)
log10 = _ew(jnp.log10)
log1p = _ew(jnp.log1p)
sin = _ew(jnp.sin)
cos = _ew(jnp.cos)
tan = _ew(jnp.tan)
asin = _ew(jnp.arcsin)
acos = _ew(jnp.arccos)
atan = _ew(jnp.arctan)
sinh = _ew(jnp.sinh)
cosh = _ew(jnp.cosh)
tanh = _ew(jnp.tanh)
asinh = _ew(jnp.arcsinh)
acosh = _ew(jnp.arccosh)
atanh = _ew(jnp.arctanh)
floor = _ew(jnp.floor)
ceil = _ew(jnp.ceil)
round = _ew(jnp.round)
trunc = _ew(jnp.trunc)
frac = _ew(lambda a: a - jnp.trunc(a))
sign = _ew(jnp.sign)
neg = _ew(jnp.negative)
reciprocal = _ew(jnp.reciprocal)
erf = _ew(jax.scipy.special.erf)
erfinv = _ew(jax.scipy.special.erfinv)
lgamma = _ew(jax.scipy.special.gammaln)
digamma = _ew(jax.scipy.special.digamma)
sigmoid = _ew(jax.nn.sigmoid)
logit = _ew(jax.scipy.special.logit)
angle = _ew(jnp.angle)
conj = _ew(jnp.conj)
real = _ew(jnp.real)
imag = _ew(jnp.imag)
deg2rad = _ew(jnp.deg2rad)
rad2deg = _ew(jnp.rad2deg)
rsqrt_ = rsqrt
i0 = _ew(jax.scipy.special.i0)
i1 = _ew(jax.scipy.special.i1)
nan_to_num = lambda x, nan=0.0, posinf=None, neginf=None, name=None: dispatch(
    lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf), x
)


def clip(x, min=None, max=None, name=None):
    lo = unwrap(min) if min is not None else None
    hi = unwrap(max) if max is not None else None
    return dispatch(lambda a: jnp.clip(a, lo, hi), x)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return dispatch(lambda a: scale_b * jnp.tanh(scale_a * a), x)


def increment(x, value=1.0, name=None):
    out = dispatch(lambda a: a + value, x)
    x.set_value(out._array)
    return x


# -- reductions -------------------------------------------------------------
def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    dt = dtype_mod.convert_dtype(dtype) if dtype else None
    return dispatch(lambda a: jnp.sum(a, axis=_axis(axis), dtype=dt, keepdims=keepdim), x)


def mean(x, axis=None, keepdim=False, name=None):
    return dispatch(lambda a: jnp.mean(a, axis=_axis(axis), keepdims=keepdim), x, amp_policy=BLACK)


def max(x, axis=None, keepdim=False, name=None):
    return dispatch(lambda a: jnp.max(a, axis=_axis(axis), keepdims=keepdim), x)


def min(x, axis=None, keepdim=False, name=None):
    return dispatch(lambda a: jnp.min(a, axis=_axis(axis), keepdims=keepdim), x)


def amax(x, axis=None, keepdim=False, name=None):
    return max(x, axis, keepdim)


def amin(x, axis=None, keepdim=False, name=None):
    return min(x, axis, keepdim)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    dt = dtype_mod.convert_dtype(dtype) if dtype else None
    return dispatch(lambda a: jnp.prod(a, axis=_axis(axis), dtype=dt, keepdims=keepdim), x)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return dispatch(
        lambda a: jax.scipy.special.logsumexp(a, axis=_axis(axis), keepdims=keepdim),
        x,
        amp_policy=BLACK,
    )


def cumsum(x, axis=None, dtype=None, name=None):
    def f(a):
        if axis is None:
            return jnp.cumsum(a.reshape(-1))
        return jnp.cumsum(a, axis=int(axis))

    return dispatch(f, x)


def cumprod(x, dim=None, dtype=None, name=None):
    return dispatch(lambda a: jnp.cumprod(a, axis=dim), x)


def cummax(x, axis=None, name=None):
    a = unwrap(x)
    if axis is None:
        a = a.reshape(-1)
        axis = 0
    vals = jax.lax.associative_scan(jnp.maximum, a, axis=axis)
    # index of the running max: latest position where the element equals the
    # running max (scan over (value, index) pairs keeps argmax semantics)
    idx0 = jnp.arange(a.shape[axis]).reshape(
        [-1 if d == axis % a.ndim else 1 for d in range(a.ndim)]
    )
    idx0 = jnp.broadcast_to(idx0, a.shape)

    def pick(l, r):
        lv, li = l
        rv, ri = r
        take_r = rv >= lv
        return jnp.where(take_r, rv, lv), jnp.where(take_r, ri, li)

    _, idx = jax.lax.associative_scan(pick, (a, idx0), axis=axis)
    return Tensor(vals), Tensor(idx.astype(jnp.int64))


def cummin(x, axis=None, name=None):
    a = unwrap(x)
    if axis is None:
        a = a.reshape(-1)
        axis = 0
    vals = jax.lax.associative_scan(jnp.minimum, a, axis=axis)
    idx0 = jnp.arange(a.shape[axis]).reshape(
        [-1 if d == axis % a.ndim else 1 for d in range(a.ndim)]
    )
    idx0 = jnp.broadcast_to(idx0, a.shape)

    def pick(l, r):
        lv, li = l
        rv, ri = r
        take_r = rv <= lv
        return jnp.where(take_r, rv, lv), jnp.where(take_r, ri, li)

    _, idx = jax.lax.associative_scan(pick, (a, idx0), axis=axis)
    return Tensor(vals), Tensor(idx.astype(jnp.int64))


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return Tensor(jnp.count_nonzero(unwrap(x), axis=_axis(axis), keepdims=keepdim))


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return dispatch(lambda a: jnp.nansum(a, axis=_axis(axis), keepdims=keepdim), x)


def nanmean(x, axis=None, keepdim=False, name=None):
    return dispatch(lambda a: jnp.nanmean(a, axis=_axis(axis), keepdims=keepdim), x)


# -- matmul family (MXU ops — AMP white list) -------------------------------
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)

    return dispatch(f, x, y, amp_policy=WHITE)


def mm(x, y, name=None):
    return matmul(x, y)


def bmm(x, y, name=None):
    return matmul(x, y)


def dot(x, y, name=None):
    return dispatch(lambda a, b: jnp.sum(a * b, axis=-1), x, y, amp_policy=WHITE)


def mv(x, y, name=None):
    return matmul(x, y)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return dispatch(
        lambda i, a, b: beta * i + alpha * jnp.matmul(a, b), input, x, y, amp_policy=WHITE
    )


def multiplex(inputs, index, name=None):
    idx = unwrap(index).reshape(-1)
    return dispatch(
        lambda *xs: jnp.stack(xs, axis=0)[idx, jnp.arange(idx.shape[0])], *inputs
    )


def isfinite(x, name=None):
    return Tensor(jnp.isfinite(unwrap(x)))


def isnan(x, name=None):
    return Tensor(jnp.isnan(unwrap(x)))


def isinf(x, name=None):
    return Tensor(jnp.isinf(unwrap(x)))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(jnp.isclose(unwrap(x), unwrap(y), rtol=rtol, atol=atol, equal_nan=equal_nan))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(jnp.allclose(unwrap(x), unwrap(y), rtol=rtol, atol=atol, equal_nan=equal_nan))


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return dispatch(lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2), x)
