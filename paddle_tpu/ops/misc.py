"""Long-tail operators from the reference inventory (SURVEY Appendix A).

Each op cites its reference kernel.  These are the mechanically-simple
members of the remaining op families; all are pure jnp (XLA) functions
through the standard dispatch (eager + jit + autograd).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import dispatch
from ..core.tensor import Tensor, unwrap

__all__ = [
    "add_position_encoding", "affine_channel", "anchor_generator",
    "bipartite_match", "bpr_loss", "center_loss", "ctc_align", "data_norm",
    "edit_distance", "gather_tree", "hinge_loss", "l1_norm", "mean_iou",
    "modified_huber_loss", "rank_loss", "sampling_id", "space_to_depth",
    "squared_l2_distance", "squared_l2_norm", "teacher_student_sigmoid_loss",
    "row_conv", "set_value", "segment_sum", "segment_mean", "segment_max",
    "segment_min", "segment_pool", "fsp_matrix", "Print", "Assert",
    "conv_shift", "cvm", "shuffle_batch", "hash_op", "batch_fc",
    "similarity_focus", "lookup_table_dequant",
]


def add_position_encoding(x, alpha=1.0, beta=1.0, name=None):
    """Sinusoidal position encoding added to the input
    (`operators/add_position_encoding_op.*`): out = alpha*x + beta*PE."""
    def f(a):
        b, t, d = a.shape
        pos = jnp.arange(t, dtype=jnp.float32)[:, None]
        half = d // 2
        div = jnp.power(10000.0, jnp.arange(half, dtype=jnp.float32) / half)
        ang = pos / div[None, :]
        pe = jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1)
        if pe.shape[-1] < d:
            pe = jnp.pad(pe, ((0, 0), (0, d - pe.shape[-1])))
        return alpha * a + beta * pe[None].astype(a.dtype)

    return dispatch(f, x)


def affine_channel(x, scale=None, bias=None, data_layout="NCHW", name=None):
    """Per-channel affine (`operators/affine_channel_op.*`)."""
    def f(a, s, b):
        shape = ((1, -1, 1, 1) if data_layout == "NCHW" else (1, 1, 1, -1))
        return a * s.reshape(shape) + b.reshape(shape)

    return dispatch(f, x, scale, bias)


def anchor_generator(input, anchor_sizes, aspect_ratios, variances,
                     stride, offset=0.5, name=None):
    """RPN anchor generation (`operators/detection/anchor_generator_op.*`):
    anchors [H, W, A, 4] in xyxy + matching variances."""
    h, w = int(unwrap(input).shape[2]), int(unwrap(input).shape[3])
    sw, sh = float(stride[0]), float(stride[1])
    # reference kernel semantics (anchor_generator_op.h): aspect ratios
    # OUTER loop, base w/h rounded from the stride cell area, then scaled
    ws, hs = [], []
    for ar in aspect_ratios:
        area = sw * sh
        area_ratios = area / ar
        base_w = np.round(np.sqrt(area_ratios))
        base_h = np.round(base_w * ar)
        for size in anchor_sizes:
            scale_w = size / sw
            scale_h = size / sh
            ws.append(scale_w * base_w)
            hs.append(scale_h * base_h)
    a = len(ws)
    cx = np.arange(w) * sw + offset * (sw - 1)
    cy = np.arange(h) * sh + offset * (sh - 1)
    cxg, cyg = np.meshgrid(cx, cy)
    half_w = (np.asarray(ws, np.float32) - 1.0) * 0.5
    half_h = (np.asarray(hs, np.float32) - 1.0) * 0.5
    anchors = np.stack([
        cxg[..., None] - half_w, cyg[..., None] - half_h,
        cxg[..., None] + half_w, cyg[..., None] + half_h,
    ], axis=-1).astype(np.float32)
    var = np.broadcast_to(np.asarray(variances, np.float32),
                          (h, w, a, 4)).copy()
    return Tensor(jnp.asarray(anchors)), Tensor(jnp.asarray(var))


def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=0.5, name=None):
    """Greedy bipartite matching (`operators/detection/
    bipartite_match_op.cc`): rows = ground truth, cols = priors; returns
    (match_indices [cols] int32 with -1 for unmatched, match_dist [cols]).
    Eager (host) op — the reference's is CPU-only too."""
    d = np.asarray(jax.device_get(unwrap(dist_matrix)), np.float32).copy()
    rows, cols = d.shape
    match_idx = np.full((cols,), -1, np.int64)
    match_dist = np.zeros((cols,), np.float32)
    # phase 1: global greedy bipartite; reference skips dist < kEPS, so
    # zero-overlap pairs stay unmatched
    eps = 1e-6
    work = d.copy()
    for _ in range(min(rows, cols)):
        r, c = np.unravel_index(np.argmax(work), work.shape)
        if work[r, c] < eps:
            break
        match_idx[c] = r
        match_dist[c] = d[r, c]
        work[r, :] = -1.0
        work[:, c] = -1.0
    if match_type == "per_prediction":
        # phase 2: every unmatched col above threshold takes its argmax row
        for c in range(cols):
            if match_idx[c] == -1:
                r = int(np.argmax(d[:, c]))
                if d[r, c] >= max(dist_threshold, 1e-6):
                    match_idx[c] = r
                    match_dist[c] = d[r, c]
    return (Tensor(jnp.asarray(match_idx)),
            Tensor(jnp.asarray(match_dist)))


def bpr_loss(input, label, name=None):
    """Bayesian Personalized Ranking loss (`operators/bpr_loss_op.*`):
    -mean log sigmoid(score_pos - score_neg_j) over j != pos."""
    def f(logits, y):
        n, c = logits.shape
        pos = jnp.take_along_axis(logits, y.reshape(-1, 1), axis=1)
        diff = pos - logits  # [N, C]
        logsig = jax.nn.log_sigmoid(diff)
        mask = 1.0 - jax.nn.one_hot(y.reshape(-1), c, dtype=logits.dtype)
        return -(logsig * mask).sum(axis=1, keepdims=True) / (c - 1)

    return dispatch(f, input, label, nondiff=(1,))


def center_loss(input, label, centers, alpha=0.5, update_center=True,
                name=None):
    """Center loss (`operators/center_loss_op.*`): pulls features toward
    their class center.  Returns (loss [N,1], new_centers) — the center
    update is the op's side output (reference updates in-kernel)."""
    def f(feat, y, c):
        sel = c[y]  # [N, D]
        diff = feat - sel
        loss = 0.5 * jnp.sum(diff * diff, axis=1, keepdims=True)
        if update_center:
            counts = jnp.zeros((c.shape[0],), feat.dtype).at[y].add(1.0)
            sums = jnp.zeros_like(c).at[y].add(diff)
            new_c = c + alpha * sums / (counts[:, None] + 1.0)
        else:
            new_c = c
        return loss, jax.lax.stop_gradient(new_c)

    return dispatch(f, input, label, centers, nondiff=(1, 2))


def ctc_align(input, blank=0, merge_repeated=True, padding_value=0,
              input_length=None, name=None):
    """CTC alignment decode (`operators/ctc_align_op.*`): squeeze repeats +
    drop blanks per row; output padded with padding_value (static shape)."""
    arr = np.asarray(jax.device_get(unwrap(input)))
    in_lens = (np.asarray(jax.device_get(unwrap(input_length)))
               .reshape(-1) if input_length is not None else None)
    out = np.full_like(arr, padding_value)
    lens = np.zeros((arr.shape[0],), np.int64)
    for i, row in enumerate(arr):
        prev = None
        k = 0
        n = int(in_lens[i]) if in_lens is not None else len(row)
        for v in row[:n]:
            if merge_repeated and prev is not None and v == prev:
                prev = v
                continue
            prev = v
            if v != blank:
                out[i, k] = v
                k += 1
        lens[i] = k
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(lens))


def data_norm(x, means, scales, name=None):
    """Serving-time data normalization (`operators/data_norm_op.*`):
    (x - mean) * scale with externally-maintained statistics."""
    def f(a, m, s):
        return (a - m) * s

    return dispatch(f, x, means, scales)


def edit_distance(input, label, normalized=True, input_length=None,
                  label_length=None, name=None):
    """Levenshtein distance per sequence pair
    (`operators/edit_distance_op.*`).  Host DP (the reference's is a CPU/
    small-GPU kernel); inputs are [B, T] id arrays + lengths."""
    a = np.asarray(jax.device_get(unwrap(input)))
    b = np.asarray(jax.device_get(unwrap(label)))
    il = (np.asarray(jax.device_get(unwrap(input_length)))
          if input_length is not None else np.full(a.shape[0], a.shape[1]))
    ll = (np.asarray(jax.device_get(unwrap(label_length)))
          if label_length is not None else np.full(b.shape[0], b.shape[1]))
    out = np.zeros((a.shape[0], 1), np.float32)
    seq_num = a.shape[0]
    for i in range(seq_num):
        x = a[i, : int(il[i])]
        y = b[i, : int(ll[i])]
        m, n = len(x), len(y)
        dp = np.arange(n + 1, dtype=np.int64)
        for r in range(1, m + 1):
            prev = dp[0]
            dp[0] = r
            for c in range(1, n + 1):
                cur = dp[c]
                dp[c] = min(dp[c] + 1, dp[c - 1] + 1,
                            prev + (x[r - 1] != y[c - 1]))
                prev = cur
        d = float(dp[n])
        out[i, 0] = d / max(n, 1) if normalized else d
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(
        np.full((1,), seq_num, np.int64)))


def gather_tree(ids, parents, name=None):
    """Beam-search backtrace (`operators/gather_tree_op.*`):
    ids/parents [T, B, W] -> full sequences by walking parent pointers."""
    def f(idv, par):
        t, b, w = idv.shape

        def step(beams, i):
            # beams: [B, W] current beam index at time i+1
            rows = t - 1 - i
            out = jnp.take_along_axis(idv[rows], beams, axis=-1)
            nxt = jnp.take_along_axis(par[rows], beams, axis=-1)
            return nxt, out

        init = jnp.broadcast_to(jnp.arange(w), (b, w))
        _, outs = jax.lax.scan(step, init, jnp.arange(t))
        return outs[::-1]  # [T, B, W]

    return dispatch(f, ids, parents, nondiff=(0, 1))


def hinge_loss(input, label, name=None):
    """(`operators/hinge_loss_op.*`): max(0, 1 - pred * (2y - 1))."""
    def f(p, y):
        return jnp.maximum(0.0, 1.0 - p * (2.0 * y - 1.0))

    return dispatch(f, input, label)


def l1_norm(x, name=None):
    """(`operators/l1_norm_op.*`): sum(|x|)."""
    return dispatch(lambda a: jnp.sum(jnp.abs(a)), x)


def mean_iou(input, label, num_classes, name=None):
    """Segmentation mean-IoU (`operators/mean_iou_op.*`): returns
    (mean_iou scalar, out_wrong [C], out_correct [C])."""
    def f(pred, y):
        p = pred.reshape(-1)
        t = y.reshape(-1)
        conf = jnp.zeros((num_classes, num_classes), jnp.float32).at[
            t, p].add(1.0)
        inter = jnp.diag(conf)
        union = conf.sum(0) + conf.sum(1) - inter
        valid = union > 0
        iou = jnp.where(valid, inter / jnp.maximum(union, 1.0), 0.0)
        miou = iou.sum() / jnp.maximum(valid.sum(), 1)
        correct = inter
        # reference mean_iou_op.h: a mismatch increments BOTH the label
        # class and the predicted class (so wrong + correct == union)
        wrong = conf.sum(0) + conf.sum(1) - 2.0 * inter
        return miou, wrong, correct

    return dispatch(f, input, label, nondiff=(0, 1))


def modified_huber_loss(input, label, name=None):
    """(`operators/modified_huber_loss_op.*`), y in {0,1}:
    z = pred*(2y-1); z >= -1: max(0, 1-z)^2 else -4z."""
    def f(p, y):
        z = p * (2.0 * y - 1.0)
        return jnp.where(z >= -1.0, jnp.square(jnp.maximum(0.0, 1.0 - z)),
                         -4.0 * z)

    return dispatch(f, input, label)


def rank_loss(label, left, right, name=None):
    """RankNet pairwise loss (`operators/rank_loss_op.*`)."""
    def f(y, l, r):
        d = l - r
        return jnp.log1p(jnp.exp(d)) - y * d

    return dispatch(f, label, left, right)


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="int64", name=None):
    """Sample a category id per row from probability rows
    (`operators/sampling_id_op.*`).  seed != 0 gives a deterministic
    per-call stream (reference seeds a local engine); dtype is honored."""
    from ..core import dtype as dtype_mod
    from ..core import framework

    key = (jax.random.PRNGKey(seed) if seed
           else framework.default_generator.next_key())
    dt = dtype_mod.convert_dtype(dtype)

    def f(p):
        # reference SamplingIdKernel: r ~ Uniform(min, max), then walk the
        # CDF; r beyond the total mass falls back to the last index
        n = p.shape[0]
        r = jax.random.uniform(key, (n,), jnp.float32,
                               jnp.float32(min), jnp.float32(max))
        cdf = jnp.cumsum(p, axis=-1)
        ids = jnp.argmax(cdf >= r[:, None], axis=-1)
        ids = jnp.where(r > cdf[:, -1], p.shape[1] - 1, ids)
        return ids.astype(dt)

    return dispatch(f, x, nondiff=(0,))


def space_to_depth(x, blocksize, name=None):
    """(`operators/space_to_depth_op.h`): darknet-reorg rearrange
    [N,C,H,W] -> [N, C*bs*bs, H/bs, W/bs].  The reference functor writes
    through an intermediate [N, C/bs^2, H*bs, W*bs] interpretation of the
    flat buffer; reproduced exactly (requires C % bs^2 == 0)."""
    bs = int(blocksize)
    c_in = int(unwrap(x).shape[1])
    if c_in % (bs * bs):
        raise ValueError(
            f"space_to_depth: channel {c_in} must divide blocksize^2 "
            f"{bs * bs} (reference PADDLE_ENFORCE)")

    def f(a):
        n, c, h, w = a.shape
        out_c = c // (bs * bs)
        # k = offset*out_c + c2, offset = oy*bs + ox
        xr = a.reshape(n, bs, bs, out_c, h, w)  # (b, oy, ox, c2, j, i)
        interp = xr.transpose(0, 3, 4, 1, 5, 2)  # (b, c2, j, oy, i, ox)
        interp = interp.reshape(n, out_c, h * bs, w * bs)
        return interp.reshape(n, c * bs * bs, h // bs, w // bs)

    return dispatch(f, x)


def squared_l2_distance(x, y, name=None):
    """(`operators/squared_l2_distance_op.*`): per-row sum((x-y)^2).
    Returns (distance [N,1], sub [N,D])."""
    def f(a, b):
        sub = a - b
        return jnp.sum(sub * sub, axis=-1, keepdims=True), sub

    return dispatch(f, x, y)


def squared_l2_norm(x, name=None):
    """(`operators/squared_l2_norm_op.*`): sum(x^2)."""
    return dispatch(lambda a: jnp.sum(a * a), x)


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0, name=None):
    """(`operators/teacher_student_sigmoid_loss_op.h`): distillation loss.
    Label encoding (reference comment): -2 = no teacher, no click;
    -1 = no teacher, click; [0,1) = teacher score z', no click;
    [1,2] = 1 + z', click.  loss = hard-CE(z) [+ soft-CE(z') if teacher].
    (The reference clips x only inside its hand-written gradient; autograd
    here differentiates the forward, so bounds affect nothing numerically
    for |x| within them.)"""
    def f(x, y):
        softplus = jnp.maximum(x, 0.0) + jnp.log1p(jnp.exp(-jnp.abs(x)))
        no_teacher_noclk = softplus                 # label < -1
        no_teacher_clk = softplus - x               # -1 <= label < 0
        teacher_noclk = softplus + softplus - x * y  # 0 <= label < 1
        teacher_clk = (softplus - x) + softplus - x * (y - 1.0)  # label >= 1
        return jnp.where(
            y < -1.0, no_teacher_noclk,
            jnp.where(y < 0.0, no_teacher_clk,
                      jnp.where(y < 1.0, teacher_noclk, teacher_clk)))

    return dispatch(f, input, label)


def row_conv(input, weight, name=None):
    """Lookahead row convolution (`operators/row_conv_op.*`, DeepSpeech2):
    input [B, T, D]; weight [future_context + 1, D]."""
    def f(a, w):
        ctx = w.shape[0]
        b, t, d = a.shape
        pad = jnp.pad(a, ((0, 0), (0, ctx - 1), (0, 0)))
        out = jnp.zeros_like(a)
        for k in range(ctx):  # small static context: unrolled adds fuse
            out = out + pad[:, k: k + t, :] * w[k][None, None, :]
        return out

    return dispatch(f, input, weight)


def set_value(x, value, slices=None, name=None):
    """Static slice assignment (`operators/set_value_op.*`): functional
    form of the reference's in-place `x[slices] = value`; returns the
    updated tensor (Tensor.__setitem__ wraps this for the eager API)."""
    from ..core.tensor import Tensor, unwrap

    val = value if isinstance(value, Tensor) else \
        Tensor(jnp.asarray(unwrap(value)))
    return dispatch(lambda a, v: (a.at[slices].set(v.astype(a.dtype))
                                  if slices is not None else
                                  jnp.broadcast_to(v, a.shape)
                                  .astype(a.dtype)), x, val)


def segment_sum(data, segment_ids, name=None):
    """`operators/segment_pool_op.*` SUM (jax.ops.segment_sum over the
    leading axis; segment count = max(id)+1, host-known)."""
    import numpy as np

    from ..core.tensor import unwrap

    n_seg = int(np.asarray(jax.device_get(unwrap(segment_ids))).max()) + 1
    return dispatch(
        lambda d, s: jax.ops.segment_sum(d, s.astype(jnp.int32),
                                         num_segments=n_seg),
        data, segment_ids, nondiff=(1,))


def _segment_reduce(data, segment_ids, mode):
    import numpy as np

    from ..core.tensor import unwrap

    n_seg = int(np.asarray(jax.device_get(unwrap(segment_ids))).max()) + 1

    def f(d, s):
        s = s.astype(jnp.int32)
        if mode == "mean":
            tot = jax.ops.segment_sum(d, s, num_segments=n_seg)
            cnt = jax.ops.segment_sum(jnp.ones(s.shape[0], d.dtype), s,
                                      num_segments=n_seg)
            shape = (-1,) + (1,) * (d.ndim - 1)
            return tot / jnp.maximum(cnt, 1).reshape(shape)
        if mode == "max":
            return jax.ops.segment_max(d, s, num_segments=n_seg)
        if mode == "min":
            return jax.ops.segment_min(d, s, num_segments=n_seg)
        raise ValueError(mode)

    return dispatch(f, data, segment_ids, nondiff=(1,))


def segment_mean(data, segment_ids, name=None):
    return _segment_reduce(data, segment_ids, "mean")


def segment_max(data, segment_ids, name=None):
    return _segment_reduce(data, segment_ids, "max")


def segment_min(data, segment_ids, name=None):
    return _segment_reduce(data, segment_ids, "min")


def segment_pool(data, segment_ids, pool_type="sum", name=None):
    """Dispatching wrapper matching the reference op's pooltype attr."""
    pt = pool_type.lower()
    if pt == "sum":
        return segment_sum(data, segment_ids)
    return _segment_reduce(data, segment_ids, pt)


def fsp_matrix(x, y, name=None):
    """Flow-of-solution-procedure matrix (`operators/fsp_op.*`, knowledge
    distillation): [N, Cx, H, W] x [N, Cy, H, W] -> [N, Cx, Cy] =
    x_flat @ y_flat^T / (H*W)."""
    def f(a, b):
        n, cx, h, w = a.shape
        af = a.reshape(n, cx, h * w)
        bf = b.reshape(n, b.shape[1], h * w)
        return jnp.einsum("ncs,nds->ncd", af, bf) / (h * w)

    return dispatch(f, x, y)


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both", name=None):
    """`operators/controlflow/print_op`-style debug print; inside jit it
    lowers to jax.debug.print (host callback), eagerly it prints now."""
    from ..core import framework
    from ..core.tensor import unwrap

    msg = message or ""
    arr = unwrap(input)
    if framework.in_trace():
        jax.debug.print(msg + " {x}", x=arr)
    else:
        import numpy as np

        vals = np.asarray(jax.device_get(arr)).ravel()[:summarize]
        print(f"{msg} shape={tuple(arr.shape)} dtype={arr.dtype} "
              f"values={vals}")
    return input


def Assert(cond, data=None, summarize=20, name=None):
    """`operators/assert_op`: raise if cond is False (eager) /
    checkify-style debug check under jit."""
    from ..core import framework
    from ..core.tensor import unwrap

    arr = unwrap(cond)
    if framework.in_trace():
        def _cb(ok):
            if not bool(ok):
                raise AssertionError("paddle.static.nn.Assert failed")
        jax.debug.callback(_cb, jnp.all(arr))
        return cond
    if not bool(jax.device_get(jnp.all(arr))):
        import numpy as np

        detail = [np.asarray(jax.device_get(unwrap(d))).ravel()[:summarize]
                  for d in (data or [])]
        raise AssertionError(f"Assert failed; data={detail}")
    return cond


def conv_shift(x, y, name=None):
    """Circular convolution (`operators/conv_shift_op.cc`):
    out[i, j] = sum_k x[i, (j + k - y_half) mod W] * y[i, k] with
    W = x.shape[1], y_half = y.shape[1] // 2."""

    def f(xv, yv):
        w = xv.shape[1]
        m = yv.shape[1]
        half = m // 2
        j = jnp.arange(w)[:, None]
        k = jnp.arange(m)[None, :]
        idx = (j + k - half) % w  # [W, M]
        return jnp.einsum("bwm,bm->bw", xv[:, idx], yv)

    return dispatch(f, x, y)


def cvm(x, cvm_input, use_cvm=True, name=None):
    """Continuous-value model op (`operators/cvm_op.h`): the first two
    columns are show/click counters; use_cvm=True log-transforms them in
    place (log(show+1), log(click+1)-log(show+1)), use_cvm=False drops
    them."""

    def f(xv, _cvm):
        if use_cvm:
            c0 = jnp.log(xv[:, :1] + 1.0)
            c1 = jnp.log(xv[:, 1:2] + 1.0) - c0
            return jnp.concatenate([c0, c1, xv[:, 2:]], axis=1)
        return xv[:, 2:]

    return dispatch(f, x, cvm_input, nondiff=(1,))


def shuffle_batch(x, seed=0, name=None):
    """Random row permutation (`operators/shuffle_batch_op.cc`); returns
    (shuffled, shuffle_idx, seed_out) like the reference (the index output
    lets callers un-shuffle)."""

    def f(xv):
        key = jax.random.PRNGKey(int(seed))
        perm = jax.random.permutation(key, xv.shape[0])
        return xv[perm], perm.astype(jnp.int64)

    out, idx = dispatch(f, x)
    return out, idx, Tensor(jnp.asarray([seed], jnp.int32))


def hash_op(x, num_hash=1, mod_by=100000000, name=None):
    """Feature hashing (`operators/hash_op.cc`, xxhash-based in the
    reference).  Each int row [D] hashes to `num_hash` buckets via
    distinct FNV-1a style mixes mod `mod_by`; output [N, num_hash, 1].
    Divergence: the mix function is FNV-1a rather than xxhash (bucket
    distribution is equivalent for embedding lookup purposes)."""

    def f(xv):
        v = xv.astype(jnp.uint32).reshape(xv.shape[0], -1)

        def one_hash(i):
            h = jnp.full((v.shape[0],), jnp.uint32(2166136261 + i * 16777619))
            for d in range(v.shape[1]):
                h = (h ^ v[:, d]) * jnp.uint32(16777619)
            return (h % jnp.uint32(mod_by)).astype(jnp.int64)

        return jnp.stack([one_hash(i) for i in range(num_hash)],
                         axis=1)[:, :, None]

    return dispatch(f, x, nondiff=(0,))


def batch_fc(x, w, bias=None, name=None):
    """Per-slot batched FC (`operators/batch_fc_op.cu`): x [S, N, I],
    w [S, I, O], bias [S, O] -> out [S, N, O] (one independent fc per
    slot pair — CTR models)."""

    def f(xv, wv, *b):
        out = jnp.einsum("sni,sio->sno", xv, wv)
        if b:
            out = out + b[0][:, None, :]
        return out

    args = (x, w) + ((bias,) if bias is not None else ())
    return dispatch(f, *args)


def similarity_focus(x, axis, indexes, name=None):
    """Similarity-focus mask (`operators/similarity_focus_op.cc`): for
    each index along `axis`, greedily pick maxima of the [B, C] slice so
    each row/column is used at most once, mark those positions 1, OR over
    indexes, broadcast back to x's shape.  Host-side eager op (the
    reference is CPU-only), matching bipartite_match's pattern."""
    arr = np.asarray(jax.device_get(unwrap(x)), np.float32)
    n, c, h, w = arr.shape
    if axis not in (1, 2, 3):
        raise ValueError("similarity_focus: axis must be 1, 2 or 3")
    if not indexes:
        raise ValueError("similarity_focus: indexes must be non-empty")
    out = np.zeros_like(arr)
    for b in range(n):
        mask = None
        for idx in indexes:
            t = np.take(arr[b], int(idx), axis=axis - 1)  # 2-D slice
            r, cc = t.shape
            used_r = np.zeros(r, bool)
            used_c = np.zeros(cc, bool)
            m = np.zeros((r, cc), bool)
            order = np.argsort(-t, axis=None)
            picked = 0
            for flat in order:
                i, j = divmod(int(flat), cc)
                if used_r[i] or used_c[j]:
                    continue
                m[i, j] = True
                used_r[i] = used_c[j] = True
                picked += 1
                if picked == min(r, cc):
                    break
            mask = m if mask is None else (mask | m)
        # broadcast over the reduced axis
        full = np.expand_dims(mask, axis - 1)
        out[b] = np.broadcast_to(full, arr[b].shape)
    return Tensor(jnp.asarray(out))


def lookup_table_dequant(w, ids, pow_2_bits=8, name=None):
    """Quantized embedding lookup (`operators/lookup_table_dequant_op.h`):
    each table row packs [min, max] as two float32 then uint8 codes in
    the remaining float32 payload; out = (max-min)/2^bits * code + min.
    w: [V, 2 + ceil(D/4)] float32; ids: int; returns [..., D] with
    D = (row_width - 2) * 4."""

    def f(wv, iv):
        rows = wv[iv.reshape(-1)]                    # [N, 2 + P]
        mn = rows[:, 0:1]
        mx = rows[:, 1:2]
        payload = rows[:, 2:]
        codes = jax.lax.bitcast_convert_type(payload, jnp.uint8)
        codes = codes.reshape(rows.shape[0], -1).astype(jnp.float32)
        scale = (mx - mn) / float(2 ** pow_2_bits)
        out = scale * codes + mn
        return out.reshape(iv.shape + (out.shape[-1],))

    return dispatch(f, w, ids, nondiff=(0, 1))
