"""Sequence operators — the LoD (ragged) family, TPU-native.

Reference: `paddle/fluid/operators/sequence_ops/` (6.2 k LoC of LoD-walking
CPU/CUDA kernels) and the LoDTensor ragged representation
(`framework/lod_tensor.h:109`).

TPU-native re-design: LoD offsets do not compile on a static-shape compiler,
so the canonical ragged representation here is **(padded dense tensor,
lengths vector)** — exactly what `sequence_pad`/`sequence_unpad` convert
to/from in the reference.  Every op takes either a padded [B, T, ...] batch
with a [B] lengths tensor, which jits cleanly and vectorizes on the VPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import dispatch
from ..core.tensor import Tensor, unwrap

__all__ = [
    "sequence_mask", "sequence_pad", "sequence_unpad", "sequence_pool",
    "sequence_reverse", "sequence_softmax", "sequence_expand",
    "sequence_first_step", "sequence_last_step",
]


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """lengths [..] -> mask [.., maxlen].
    Reference: `operators/sequence_ops/sequence_mask_op.*`."""
    import numpy as np

    from ..core import dtype as dtype_mod

    lengths = unwrap(x)
    if maxlen is None:
        maxlen = int(np.asarray(jax.device_get(lengths)).max())
    dt = dtype_mod.convert_dtype(dtype)

    def f(ln):
        rng = jnp.arange(maxlen)
        return (rng[None, :] < ln.reshape(-1, 1)).reshape(
            tuple(ln.shape) + (maxlen,)).astype(dt)

    return dispatch(f, x, nondiff=(0,))


def sequence_pad(x, lengths, pad_value=0.0, maxlen=None, name=None):
    """List of variable-length rows packed as [sum(L), ...] + lengths ->
    padded [B, maxlen, ...].  Reference: `sequence_pad_op.*` (input LoD ->
    padded + Length output)."""
    ln = unwrap(lengths)
    b = int(ln.shape[0])
    flat = unwrap(x)
    if maxlen is None:
        import numpy as np

        maxlen = int(np.asarray(jax.device_get(ln)).max())

    def f(xv, lv):
        starts = jnp.concatenate([jnp.zeros((1,), lv.dtype),
                                  jnp.cumsum(lv)[:-1]])
        idx = starts[:, None] + jnp.arange(maxlen)[None, :]  # [B, T]
        valid = jnp.arange(maxlen)[None, :] < lv[:, None]
        gathered = xv[jnp.clip(idx, 0, xv.shape[0] - 1)]
        mask = valid.reshape(valid.shape + (1,) * (xv.ndim - 1))
        return jnp.where(mask, gathered, pad_value)

    return dispatch(f, x, lengths, nondiff=(1,)), Tensor(ln)


def sequence_unpad(x, length, name=None):
    """Padded [B, T, ...] + lengths -> packed [sum(L), ...].
    Reference: `sequence_unpad_op.*` (with `sequence_unpad_grad`).  Output
    length is data-dependent, so lengths sync to host (eager flush point,
    like the reference's LoD re-pack), but the gather itself goes through
    dispatch so gradients flow back to `x`."""
    import numpy as np

    xv = unwrap(x)
    ln = np.asarray(jax.device_get(unwrap(length)), np.int64)
    t = xv.shape[1]
    flat_idx = np.concatenate(
        [i * t + np.arange(int(ln[i])) for i in range(xv.shape[0])]
    ).astype(np.int32) if len(ln) else np.zeros((0,), np.int32)

    def f(arr):
        return arr.reshape((-1,) + arr.shape[2:])[jnp.asarray(flat_idx)]

    return dispatch(f, x)


def sequence_pool(x, lengths, pool_type="sum", name=None):
    """Padded [B, T, ...] + lengths -> [B, ...] pooled over valid steps.
    Reference: `sequence_pool_op.*` / `operators/math/sequence_pooling.*`
    (SUM/MEAN/MAX/SQRT/LAST/FIRST)."""
    pt = pool_type.lower()

    def f(xv, lv):
        t = xv.shape[1]
        mask = (jnp.arange(t)[None, :] < lv[:, None])
        m = mask.reshape(mask.shape + (1,) * (xv.ndim - 2)).astype(xv.dtype)
        if pt == "sum":
            return (xv * m).sum(axis=1)
        if pt == "average" or pt == "mean":
            return (xv * m).sum(axis=1) / jnp.maximum(
                lv.astype(xv.dtype), 1).reshape((-1,) + (1,) * (xv.ndim - 2))
        if pt == "sqrt":
            return (xv * m).sum(axis=1) / jnp.sqrt(jnp.maximum(
                lv.astype(xv.dtype), 1)).reshape((-1,) + (1,) * (xv.ndim - 2))
        if pt == "max":
            neg = jnp.where(m > 0, xv, jnp.finfo(xv.dtype).min)
            res = neg.max(axis=1)
            # zero-length sequences pool to 0, matching pad semantics
            nz = (lv > 0).reshape((-1,) + (1,) * (xv.ndim - 2))
            return jnp.where(nz, res, jnp.zeros_like(res))
        if pt == "last":
            idx = jnp.maximum(lv - 1, 0)
            return jnp.take_along_axis(
                xv, idx.reshape((-1, 1) + (1,) * (xv.ndim - 2)), axis=1
            ).squeeze(1)
        if pt == "first":
            return xv[:, 0]
        raise ValueError(f"unknown pool_type {pool_type}")

    return dispatch(f, x, lengths, nondiff=(1,))


def sequence_first_step(x, lengths, name=None):
    return sequence_pool(x, lengths, "first")


def sequence_last_step(x, lengths, name=None):
    return sequence_pool(x, lengths, "last")


def sequence_reverse(x, lengths, name=None):
    """Reverse each sequence within its valid length (padding stays put).
    Reference: `sequence_reverse_op.*`."""
    def f(xv, lv):
        t = xv.shape[1]
        rng = jnp.arange(t)[None, :]
        rev = lv[:, None] - 1 - rng
        idx = jnp.where(rng < lv[:, None], rev, rng).astype(jnp.int32)
        idx = idx.reshape(idx.shape + (1,) * (xv.ndim - 2))
        return jnp.take_along_axis(xv, idx, axis=1)

    return dispatch(f, x, lengths, nondiff=(1,))


def sequence_softmax(x, lengths, name=None):
    """Masked softmax over the time axis.
    Reference: `sequence_softmax_op.*` (per-sequence softmax over LoD rows)."""
    def f(xv, lv):
        t = xv.shape[1]
        mask = jnp.arange(t)[None, :] < lv[:, None]
        mask = mask.reshape(mask.shape + (1,) * (xv.ndim - 2))
        # finite mask value: softmax over an all -inf row yields NaN, which
        # 0*NaN would leak through the where in the backward pass
        z = jnp.where(mask, xv, jnp.finfo(xv.dtype).min)
        out = jax.nn.softmax(z, axis=1)
        return jnp.where(mask, out, 0.0)

    return dispatch(f, x, lengths, nondiff=(1,))


def sequence_expand(x, repeat_times, name=None):
    """Repeat each row i of x `repeat_times[i]` times (static repeats).
    Reference: `sequence_expand_op.*` expands rows per the target LoD; the
    TPU form takes explicit per-row repeat counts (host-known, so shapes
    stay static)."""
    import numpy as np

    reps = np.asarray(jax.device_get(unwrap(repeat_times)), np.int64) \
        if not isinstance(repeat_times, (list, tuple)) else \
        np.asarray(repeat_times, np.int64)

    def f(xv):
        return jnp.repeat(xv, jnp.asarray(reps), axis=0,
                          total_repeat_length=int(reps.sum()))

    return dispatch(f, x)
