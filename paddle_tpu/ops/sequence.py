"""Sequence operators — the LoD (ragged) family, TPU-native.

Reference: `paddle/fluid/operators/sequence_ops/` (6.2 k LoC of LoD-walking
CPU/CUDA kernels) and the LoDTensor ragged representation
(`framework/lod_tensor.h:109`).

TPU-native re-design: LoD offsets do not compile on a static-shape compiler,
so the canonical ragged representation here is **(padded dense tensor,
lengths vector)** — exactly what `sequence_pad`/`sequence_unpad` convert
to/from in the reference.  Every op takes either a padded [B, T, ...] batch
with a [B] lengths tensor, which jits cleanly and vectorizes on the VPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import dispatch
from ..core.tensor import Tensor, unwrap

__all__ = [
    "sequence_mask", "sequence_pad", "sequence_unpad", "sequence_pool",
    "sequence_reverse", "sequence_softmax", "sequence_expand",
    "sequence_first_step", "sequence_last_step", "sequence_concat",
    "sequence_expand_as", "sequence_enumerate", "sequence_erase",
    "sequence_reshape", "sequence_scatter", "sequence_slice",
    "sequence_topk_avg_pooling", "sequence_conv",
]


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """lengths [..] -> mask [.., maxlen].
    Reference: `operators/sequence_ops/sequence_mask_op.*`."""
    import numpy as np

    from ..core import dtype as dtype_mod

    lengths = unwrap(x)
    if maxlen is None:
        maxlen = int(np.asarray(jax.device_get(lengths)).max())
    dt = dtype_mod.convert_dtype(dtype)

    def f(ln):
        rng = jnp.arange(maxlen)
        return (rng[None, :] < ln.reshape(-1, 1)).reshape(
            tuple(ln.shape) + (maxlen,)).astype(dt)

    return dispatch(f, x, nondiff=(0,))


def sequence_pad(x, lengths, pad_value=0.0, maxlen=None, name=None):
    """List of variable-length rows packed as [sum(L), ...] + lengths ->
    padded [B, maxlen, ...].  Reference: `sequence_pad_op.*` (input LoD ->
    padded + Length output)."""
    ln = unwrap(lengths)
    b = int(ln.shape[0])
    flat = unwrap(x)
    if maxlen is None:
        import numpy as np

        maxlen = int(np.asarray(jax.device_get(ln)).max())

    def f(xv, lv):
        starts = jnp.concatenate([jnp.zeros((1,), lv.dtype),
                                  jnp.cumsum(lv)[:-1]])
        idx = starts[:, None] + jnp.arange(maxlen)[None, :]  # [B, T]
        valid = jnp.arange(maxlen)[None, :] < lv[:, None]
        gathered = xv[jnp.clip(idx, 0, xv.shape[0] - 1)]
        mask = valid.reshape(valid.shape + (1,) * (xv.ndim - 1))
        return jnp.where(mask, gathered, pad_value)

    return dispatch(f, x, lengths, nondiff=(1,)), Tensor(ln)


def sequence_unpad(x, length, name=None):
    """Padded [B, T, ...] + lengths -> packed [sum(L), ...].
    Reference: `sequence_unpad_op.*` (with `sequence_unpad_grad`).  Output
    length is data-dependent, so lengths sync to host (eager flush point,
    like the reference's LoD re-pack), but the gather itself goes through
    dispatch so gradients flow back to `x`."""
    import numpy as np

    xv = unwrap(x)
    ln = np.asarray(jax.device_get(unwrap(length)), np.int64)
    t = xv.shape[1]
    flat_idx = np.concatenate(
        [i * t + np.arange(int(ln[i])) for i in range(xv.shape[0])]
    ).astype(np.int32) if len(ln) else np.zeros((0,), np.int32)

    def f(arr):
        return arr.reshape((-1,) + arr.shape[2:])[jnp.asarray(flat_idx)]

    return dispatch(f, x)


def sequence_pool(x, lengths, pool_type="sum", name=None):
    """Padded [B, T, ...] + lengths -> [B, ...] pooled over valid steps.
    Reference: `sequence_pool_op.*` / `operators/math/sequence_pooling.*`
    (SUM/MEAN/MAX/SQRT/LAST/FIRST)."""
    pt = pool_type.lower()

    def f(xv, lv):
        t = xv.shape[1]
        mask = (jnp.arange(t)[None, :] < lv[:, None])
        m = mask.reshape(mask.shape + (1,) * (xv.ndim - 2)).astype(xv.dtype)
        if pt == "sum":
            return (xv * m).sum(axis=1)
        if pt == "average" or pt == "mean":
            return (xv * m).sum(axis=1) / jnp.maximum(
                lv.astype(xv.dtype), 1).reshape((-1,) + (1,) * (xv.ndim - 2))
        if pt == "sqrt":
            return (xv * m).sum(axis=1) / jnp.sqrt(jnp.maximum(
                lv.astype(xv.dtype), 1)).reshape((-1,) + (1,) * (xv.ndim - 2))
        if pt == "max":
            neg = jnp.where(m > 0, xv, jnp.finfo(xv.dtype).min)
            res = neg.max(axis=1)
            # zero-length sequences pool to 0, matching pad semantics
            nz = (lv > 0).reshape((-1,) + (1,) * (xv.ndim - 2))
            return jnp.where(nz, res, jnp.zeros_like(res))
        if pt == "last":
            idx = jnp.maximum(lv - 1, 0)
            return jnp.take_along_axis(
                xv, idx.reshape((-1, 1) + (1,) * (xv.ndim - 2)), axis=1
            ).squeeze(1)
        if pt == "first":
            return xv[:, 0]
        raise ValueError(f"unknown pool_type {pool_type}")

    return dispatch(f, x, lengths, nondiff=(1,))


def sequence_first_step(x, lengths, name=None):
    return sequence_pool(x, lengths, "first")


def sequence_last_step(x, lengths, name=None):
    return sequence_pool(x, lengths, "last")


def sequence_reverse(x, lengths, name=None):
    """Reverse each sequence within its valid length (padding stays put).
    Reference: `sequence_reverse_op.*`."""
    def f(xv, lv):
        t = xv.shape[1]
        rng = jnp.arange(t)[None, :]
        rev = lv[:, None] - 1 - rng
        idx = jnp.where(rng < lv[:, None], rev, rng).astype(jnp.int32)
        idx = idx.reshape(idx.shape + (1,) * (xv.ndim - 2))
        return jnp.take_along_axis(xv, idx, axis=1)

    return dispatch(f, x, lengths, nondiff=(1,))


def sequence_softmax(x, lengths, name=None):
    """Masked softmax over the time axis.
    Reference: `sequence_softmax_op.*` (per-sequence softmax over LoD rows)."""
    def f(xv, lv):
        t = xv.shape[1]
        mask = jnp.arange(t)[None, :] < lv[:, None]
        mask = mask.reshape(mask.shape + (1,) * (xv.ndim - 2))
        # finite mask value: softmax over an all -inf row yields NaN, which
        # 0*NaN would leak through the where in the backward pass
        z = jnp.where(mask, xv, jnp.finfo(xv.dtype).min)
        out = jax.nn.softmax(z, axis=1)
        return jnp.where(mask, out, 0.0)

    return dispatch(f, x, lengths, nondiff=(1,))


def sequence_expand(x, repeat_times, name=None):
    """Repeat each row i of x `repeat_times[i]` times (static repeats).
    Reference: `sequence_expand_op.*` expands rows per the target LoD; the
    TPU form takes explicit per-row repeat counts (host-known, so shapes
    stay static)."""
    import numpy as np

    reps = np.asarray(jax.device_get(unwrap(repeat_times)), np.int64) \
        if not isinstance(repeat_times, (list, tuple)) else \
        np.asarray(repeat_times, np.int64)

    def f(xv):
        return jnp.repeat(xv, jnp.asarray(reps), axis=0,
                          total_repeat_length=int(reps.sum()))

    return dispatch(f, x)


def sequence_concat(xs, lengths_list, name=None):
    """Concatenate the i-th sequences of every input along time
    (`sequence_ops/sequence_concat_op.*`).  Padded form: inputs
    [B, Ti, ...] with lengths [B]; output [B, sum(Ti), ...] where row b
    holds the valid parts back to back, zero-padded after."""
    k = len(xs)
    T_out = sum(unwrap(x).shape[1] for x in xs)

    def f(*ops):
        xs_, ls_ = ops[:k], ops[k:]
        b = xs_[0].shape[0]
        feat = xs_[0].shape[2:]
        out = jnp.zeros((b, T_out) + feat, xs_[0].dtype)
        tpos = jnp.arange(T_out)
        offset = jnp.zeros(b, jnp.int32)
        for xv, lv in zip(xs_, ls_):
            lv = lv.astype(jnp.int32)
            ti = xv.shape[1]
            # scatter xv[b, :lv[b]] at out[b, offset[b]:offset[b]+lv[b]]
            src_idx = tpos[None, :] - offset[:, None]  # [B, T_out]
            valid = (src_idx >= 0) & (src_idx < lv[:, None])
            gathered = jnp.take_along_axis(
                xv, jnp.clip(src_idx, 0, ti - 1).reshape(
                    (b, T_out) + (1,) * len(feat)), axis=1)
            out = jnp.where(valid.reshape((b, T_out) + (1,) * len(feat)),
                            gathered, out)
            offset = offset + lv
        return out, offset

    # through dispatch so gradients flow back into every input sequence
    return dispatch(f, *xs, *lengths_list,
                    nondiff=tuple(range(k, 2 * k)))


def sequence_expand_as(x, y_lengths, name=None):
    """Expand each row of x to the length of the matching y sequence
    (`sequence_ops/sequence_expand_as_op.*`): row b (one timestep) is
    broadcast y_lengths[b] times -> padded [B, maxlen, ...] + lengths."""
    import numpy as np

    ln = unwrap(y_lengths)
    maxlen = int(np.asarray(jax.device_get(ln)).max())

    def g(xv, lv):
        rep = jnp.repeat(xv[:, None, ...], maxlen, axis=1)
        mask = jnp.arange(maxlen)[None, :] < lv[:, None].astype(jnp.int32)
        return jnp.where(mask.reshape(mask.shape + (1,) *
                                      (rep.ndim - 2)), rep, 0.0)

    return dispatch(g, x, y_lengths, nondiff=(1,))


def sequence_enumerate(x, lengths, win_size, pad_value=0, name=None):
    """Sliding windows of ids (`sequence_ops/sequence_enumerate_op.*`):
    [B, T] int ids -> [B, T, win_size]; positions past a row's length (or
    past T) fill with pad_value."""
    def f(ids, lv):
        b, t = ids.shape
        base = jnp.arange(t)[:, None] + jnp.arange(win_size)[None, :]
        win = jnp.where(base < t, ids[:, jnp.clip(base, 0, t - 1)],
                        pad_value)
        valid_row = base[None, :, :] < lv[:, None, None].astype(jnp.int32)
        return jnp.where(valid_row, win, pad_value)

    return dispatch(f, x, lengths, nondiff=(0, 1))


def sequence_erase(x, lengths, tokens, name=None):
    """Remove listed tokens from each sequence, compacting left
    (`sequence_ops/sequence_erase_op.*`).  Returns (ids, new_lengths)."""
    toks = jnp.asarray(list(tokens))

    def f(ids, lv):
        b, t = ids.shape
        keep = ~jnp.isin(ids, toks) & (
            jnp.arange(t)[None, :] < lv[:, None].astype(jnp.int32))
        # stable left-compaction: order keeps first, then padding
        key = jnp.where(keep, jnp.arange(t)[None, :], t + jnp.arange(t))
        perm = jnp.argsort(key, axis=1)
        packed = jnp.take_along_axis(ids, perm, axis=1)
        new_len = keep.sum(axis=1)
        mask = jnp.arange(t)[None, :] < new_len[:, None]
        return jnp.where(mask, packed, 0), new_len.astype(jnp.int64)

    return dispatch(f, x, lengths, nondiff=(0, 1))


def sequence_reshape(x, lengths, new_dim, name=None):
    """Re-chunk each row's valid payload to `new_dim` columns
    (`sequence_ops/sequence_reshape_op.*`): row of L steps x D dims
    becomes L*D/new_dim steps x new_dim.  Requires L*D % new_dim == 0 per
    valid row (checked by the reference); padding stays zero."""
    def f(xv, lv):
        b, t, d = xv.shape
        t2 = t * d // new_dim
        out = xv.reshape(b, t2, new_dim)
        new_len = (lv.astype(jnp.int32) * d) // new_dim
        mask = jnp.arange(t2)[None, :] < new_len[:, None]
        return jnp.where(mask[:, :, None], out, 0.0), \
            new_len.astype(jnp.int64)

    return dispatch(f, x, lengths, nondiff=(1,))


def sequence_scatter(x, index, updates, index_lengths, name=None):
    """Scatter-add per-sequence updates into x
    (`sequence_ops/sequence_scatter_op.*`): for each batch row b,
    x[b, index[b, j]] += updates[b, j] for j < index_lengths[b]."""
    def f(xv, idx, upd, lv):
        b, k = idx.shape
        valid = jnp.arange(k)[None, :] < lv[:, None].astype(jnp.int32)
        contrib = jnp.where(valid[..., None] if upd.ndim == 3 else valid,
                            upd, 0.0)
        bidx = jnp.arange(b)[:, None].repeat(k, 1)
        return xv.at[bidx, idx].add(contrib)

    return dispatch(f, x, index, updates, index_lengths, nondiff=(1, 3))


def sequence_slice(x, lengths, offset, length, name=None):
    """Per-sequence slice (`sequence_ops/sequence_slice_op.*`):
    row b keeps [offset[b], offset[b]+length[b]); output padded to
    max(length) with new lengths."""
    def f(xv, lv, off, ln):
        b, t = xv.shape[:2]
        tout = xv.shape[1]
        pos = jnp.arange(tout)[None, :]
        src = pos + off[:, None].astype(jnp.int32)
        valid = pos < ln[:, None].astype(jnp.int32)
        g = jnp.take_along_axis(
            xv, jnp.clip(src, 0, t - 1).reshape(
                (b, tout) + (1,) * (xv.ndim - 2)), axis=1)
        return jnp.where(valid.reshape((b, tout) + (1,) * (xv.ndim - 2)),
                         g, 0.0), ln.astype(jnp.int64)

    return dispatch(f, x, lengths, offset, length, nondiff=(1, 2, 3))


def sequence_topk_avg_pooling(x, row_lengths, col_lengths, topks,
                              channel_num=1, name=None):
    """`sequence_ops/sequence_topk_avg_pooling_op.*`: for each (batch,
    channel, row), average the top-k values among the valid columns, for
    every k in `topks`.  x: [B, C, R, Cl] padded; returns
    [B, R, C*len(topks)]."""
    ks = list(topks)
    kmax = max(ks)

    def f(xv, rl, cl):
        b, c, r, w = xv.shape
        colmask = jnp.arange(w)[None, :] < cl[:, None].astype(jnp.int32)
        masked = jnp.where(colmask[:, None, None, :], xv, -jnp.inf)
        top = jax.lax.top_k(masked, min(kmax, w))[0]  # [B,C,R,kmax]
        outs = []
        for k in ks:
            kk = jnp.minimum(k, cl.astype(jnp.int32))  # effective k
            vals = jnp.where(jnp.arange(top.shape[-1])[None, None, None, :]
                             < kk[:, None, None, None],
                             jnp.where(jnp.isfinite(top), top, 0.0), 0.0)
            outs.append(vals.sum(-1) / jnp.maximum(kk, 1)[:, None, None])
        out = jnp.stack(outs, axis=-1)  # [B,C,R,K]
        rowmask = jnp.arange(r)[None, :] < rl[:, None].astype(jnp.int32)
        out = jnp.where(rowmask[:, None, :, None], out, 0.0)
        return out.transpose(0, 2, 1, 3).reshape(b, r, -1)

    return dispatch(f, x, row_lengths, col_lengths, nondiff=(1, 2))


def sequence_conv(x, lengths, weight, bias=None, context_length=3,
                  context_start=None, padding_data=None, name=None):
    """Context-window sequence convolution
    (`sequence_ops/sequence_conv_op.*`): each timestep's context window
    [t+start, t+start+context_length) is flattened and projected by
    `weight` [context_length*D, M].  Out-of-sequence context rows are
    zero (or `padding_data`)."""
    start = -((context_length - 1) // 2) if context_start is None \
        else context_start
    has_pad = padding_data is not None
    has_bias = bias is not None
    up_pad = max(0, -start)
    down_pad = max(0, start + context_length - 1)

    def f(xv, lv, w, *rest):
        b, t, d = xv.shape
        offs = jnp.arange(context_length) + start
        pos = jnp.arange(t)[:, None] + offs[None, :]  # [T, ctx]
        valid = (pos >= 0) & (pos < lv[:, None, None].astype(jnp.int32))
        g = xv[:, jnp.clip(pos, 0, t - 1), :]  # [B, T, ctx, D]
        if has_pad and up_pad + down_pad > 0:
            # trainable boundary rows (reference PaddingData,
            # math/context_project.h:156-199): padding_data is
            # [up_pad+down_pad, D]; an out-of-range context position p<0
            # reads up-pad row up_pad+p, p>=seq_len reads down-pad row
            # up_pad+(p-seq_len)
            pad = rest[0]
            lens = lv[:, None, None].astype(jnp.int32)
            pad_row = jnp.where(pos[None] < 0, up_pad + pos[None],
                                up_pad + pos[None] - lens)
            pad_row = jnp.clip(pad_row, 0, up_pad + down_pad - 1)
            g = jnp.where(valid[..., None], g, pad[pad_row])
        else:
            g = jnp.where(valid[..., None], g, 0.0)
        flat = g.reshape(b, t, context_length * d)
        out = flat @ w
        if has_bias:
            out = out + rest[-1]
        mask = jnp.arange(t)[None, :] < lv[:, None].astype(jnp.int32)
        return jnp.where(mask[..., None], out, 0.0)

    args = (x, lengths, weight) + \
        ((padding_data,) if has_pad else ()) + \
        ((bias,) if has_bias else ())
    return dispatch(f, *args, nondiff=(1,))
