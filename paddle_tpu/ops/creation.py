"""Creation ops.

Reference ops: fill_constant, fill_any_like, gaussian_random, uniform_random,
arange/range, linspace, eye, tril_triu, diag_v2, one_hot_v2, assign
(`paddle/fluid/operators/fill_constant_op.cc`, `range_op.cc`, …); Python API
`python/paddle/tensor/creation.py`.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ..core.dispatch import dispatch
from ..core.tensor import Tensor, to_tensor, unwrap


def _dt(dtype, default=None):
    if dtype is None:
        return default
    return dtype_mod.convert_dtype(dtype)


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in shape.numpy().tolist()]
    if isinstance(shape, (int,)):
        return [shape]
    return [int(unwrap(s)) if isinstance(s, Tensor) else int(s) for s in shape]


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape_list(shape), _dt(dtype, dtype_mod.get_default_dtype())))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape_list(shape), _dt(dtype, dtype_mod.get_default_dtype())))


def full(shape, fill_value, dtype=None, name=None):
    fill_value = unwrap(fill_value)
    return Tensor(jnp.full(_shape_list(shape), fill_value, _dt(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    return Tensor(jnp.zeros_like(unwrap(x), dtype=_dt(dtype)))


def ones_like(x, dtype=None, name=None):
    return Tensor(jnp.ones_like(unwrap(x), dtype=_dt(dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    return Tensor(jnp.full_like(unwrap(x), unwrap(fill_value), dtype=_dt(dtype)))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    start, end, step = unwrap(start), unwrap(end), unwrap(step)
    if end is None:
        start, end = 0, start
    return Tensor(jnp.arange(start, end, step, dtype=_dt(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(jnp.linspace(unwrap(start), unwrap(stop), int(unwrap(num)), dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(unwrap(start), unwrap(stop), int(num), base=base, dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype, dtype_mod.get_default_dtype())))


def assign(x, output=None):
    out = dispatch(lambda a: a + 0, x) if isinstance(x, Tensor) else to_tensor(x)
    if output is not None:
        output.set_value(out)
        return output
    return out


def clone(x):
    return assign(x)


def diag(x, offset=0, padding_value=0, name=None):
    def f(a):
        if a.ndim == 1:
            d = jnp.diag(a, k=offset)
            if padding_value != 0:
                mask = jnp.eye(*d.shape, k=offset, dtype=bool)
                d = jnp.where(mask, d, padding_value)
            return d
        return jnp.diagonal(a, offset=offset)

    return dispatch(f, x)


def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    def f(a):
        n = a.shape[-1] + abs(offset)
        out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        idx = jnp.arange(a.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        return out.at[..., r, c].set(a)

    return dispatch(f, x)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return dispatch(lambda a: jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2), x)


def tril(x, diagonal=0, name=None):
    return dispatch(lambda a: jnp.tril(a, k=diagonal), x)


def triu(x, diagonal=0, name=None):
    return dispatch(lambda a: jnp.triu(a, k=diagonal), x)


def meshgrid(*args, **kwargs):
    arrays = [unwrap(a) for a in (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args)]
    return [Tensor(a) for a in jnp.meshgrid(*arrays, indexing="ij")]


def one_hot(x, num_classes, name=None):
    import jax

    return Tensor(jax.nn.one_hot(unwrap(x), num_classes, dtype=dtype_mod.get_default_dtype()))


def complex(real, imag, name=None):
    return dispatch(lambda r, i: r + 1j * i, real, imag)
