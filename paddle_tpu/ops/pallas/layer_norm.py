"""Fused LayerNorm Pallas kernel (TPU).

Replaces the reference's dedicated fused norm kernels
(`operators/fused/fused_fc_elementwise_layernorm_op.cu`,
`operators/fused/skip_layernorm_op.*`, `operators/layer_norm_op.cu`'s
Welford block kernels): one VMEM-resident pass computes mean/rstd and the
normalized output per row tile, keeping the feature dim in lanes
(pallas_guide.md: last dim multiple of 128 maps onto the VPU lanes).

Gradient: custom_vjp that saves only x/w and recomputes the row statistics
in the backward (cheap bandwidth-bound reductions, XLA-fused) — the kernel
itself emits just the normalized output, which keeps its Mosaic layout
trivially valid (2-D blocks only) and avoids writing stats to HBM.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


def _ln_kernel(x_ref, w_ref, b_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)  # [block_rows, d]
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = (x - mean) * rstd
    y = y * w_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    o_ref[...] = y.astype(o_ref.dtype)


def _fwd_pallas(x2d, w, b, eps, block_rows=256):
    n, d = x2d.shape
    if n == 0:
        return _fwd_xla(x2d, w, b, eps)
    rows = min(block_rows, n)
    while n % rows:
        rows //= 2
    rows = max(rows, 1)
    grid = (n // rows,)
    return pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((rows, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((rows, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), x2d.dtype),
    )(x2d, w, b)


def _fwd_xla(x2d, w, b, eps):
    x32 = x2d.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (y * w + b).astype(x2d.dtype)


def _use_pallas(d: int) -> bool:
    # opt-in: measured on v5e, XLA's own LN fusion is faster at common
    # shapes; the kernel is kept for the cases (very wide d, bf16 HBM
    # pressure) where explicit tiling wins — enable via the flag
    from ...core import flags as _flags

    if not _flags.flag("use_pallas_layernorm"):
        return False
    return (_HAS_PALLAS and jax.default_backend() == "tpu" and
            d % 128 == 0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_layer_norm(x2d, w, b, eps=1e-5):
    """x2d: [rows, d]; w/b: [d].  Returns normalized [rows, d]."""
    fwd = _fwd_pallas if _use_pallas(x2d.shape[-1]) else _fwd_xla
    return fwd(x2d, w, b, eps)


def _vjp_fwd(x2d, w, b, eps):
    fwd = _fwd_pallas if _use_pallas(x2d.shape[-1]) else _fwd_xla
    return fwd(x2d, w, b, eps), (x2d, w)


def _vjp_bwd(eps, res, g):
    x2d, w = res
    x32 = x2d.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (x32 - mean) * rstd
    dw = jnp.sum(g32 * xhat, axis=0).astype(w.dtype)
    db = jnp.sum(g32, axis=0).astype(w.dtype)
    gy = g32 * w.astype(jnp.float32)
    dx = (gy - jnp.mean(gy, axis=-1, keepdims=True) -
          xhat * jnp.mean(gy * xhat, axis=-1, keepdims=True))
    dx = (dx * rstd).astype(x2d.dtype)
    return dx, dw, db


fused_layer_norm.defvjp(_vjp_fwd, _vjp_bwd)
