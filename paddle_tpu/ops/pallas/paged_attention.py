"""Ragged paged-attention decode kernel (TPU Pallas) + XLA reference.

The serving-side complement of flash_attention.py: decode-step attention
over a paged KV cache ("Ragged Paged Attention", PAPERS.md).  K/V live in
HBM as fixed-size pages indexed by a per-sequence block table; each
sequence in the batch has its own length (ragged batch), and query heads
may outnumber KV heads (grouped-query attention).

Queries are ragged too: ``q`` may carry **multiple query tokens per
sequence** (``[B, Q, Hq, D]``) with a per-sequence causal offset
(``q_offsets``) — query ``i`` of sequence ``b`` sits at absolute position
``q_offsets[b] + i`` and attends to KV positions ``<= q_offsets[b] + i``.
This is the capability the Ragged Paged Attention paper treats as table
stakes: it is what the speculative-decoding verify step (score K drafted
tokens in one pass) and chunked prefill ride on.  ``Q == 1`` with
``q_offsets == seq_lens - 1`` reduces exactly to classic single-token
decode.

Kernel shape (the TPU paged-decode idiom):

* grid ``(batch, kv_heads, pages_max)`` with the page axis fastest;
* the block table and sequence lengths ride in as **scalar-prefetch**
  operands (`pltpu.PrefetchScalarGridSpec`) so the K/V BlockSpec index
  maps can translate the streamed page number through the block table —
  the gather indirection happens in the DMA engine, not in compute;
* pages past a sequence's live range clamp their fetch index to the last
  live page (Pallas skips the re-fetch when the index repeats — same
  dead-block trick as flash_attention's causal clamps) and gate compute
  off with ``pl.when``;
* per-(batch, kv-head) online-softmax state (f32 acc / running max /
  running sum) stays resident in VMEM scratch across the page stream, so
  VMEM usage is constant in sequence length.

The XLA reference (`_xla_paged_attention`) is the numerics ground truth
and the CPU path; it mirrors `nn.functional.attention._sdpa_reference`'s
cast discipline exactly (scale in input dtype, f32 softmax) so the paged
engine bit-matches the eager concat-cache decode path.

Quantized KV pages (FLAGS_kv_quant=int8): pages may be stored as int8
with per-page, per-head symmetric scales (``scale = absmax / 127``,
the `quantization.int8` convention) in a parallel ``[Hkv, num_pages]``
f32 array per pool.  Dequantization is FUSED into the K/V loads — the
Pallas kernel scalar-prefetches the scale rows with the block tables
and multiplies each streamed page tile by its page scale in-register
after the DMA (the Tensix/TPP in-kernel-fusion framing: no separate
dequant materialization pass ever exists), and the XLA reference
dequantizes the gathered pages before the identical attention math so
the two backends stay bit-identical to each other.  The write side is
`paged_quant_write`: the serving step executables quantize every
scattered K/V chunk in-graph (per-head absmax folded into the running
page scale, existing page rows re-quantized when the scale grows —
the "refold").
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from . import flash_attention as fa

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

_LANES = 128
# minimum query-group rows per compute tile: pad the GQA group dim up to
# the f32 sublane tile (8) so the [group, head_dim] blocks map onto the
# VPU/MXU without sub-tile layouts
_MIN_GROUP_ROWS = 8
# page floor: below 16 tokens the per-page DMA descriptor overhead beats
# the payload (the page-axis analog of pick_blocks' 128 floor)
_MIN_PAGE_SIZE = 16
_DEFAULT_PAGE_SIZE = 64


# ---------------------------------------------------------------------------
# Page-size selection — the pick_blocks/cached_blocks machinery from
# flash_attention applied to the page axis: explicit caller values win,
# then a measured winner from the shared autotune cache (validated through
# the same shrink rules so a stale/hand-edited entry degrades instead of
# crashing the pool constructor), then the power-of-two shrink default.
# ---------------------------------------------------------------------------
def pick_page_size(max_len: int, page_size: int = _DEFAULT_PAGE_SIZE):
    """Largest power-of-two page (floor _MIN_PAGE_SIZE) that tiles
    ``max_len`` — pick_blocks' shrink rule on the page axis; None when no
    page size tiles the budget."""
    while page_size > _MIN_PAGE_SIZE and max_len % page_size:
        page_size //= 2
    if page_size < _MIN_PAGE_SIZE or max_len % page_size:
        return None
    return page_size


def _paged_key(max_len, d, dtype):
    # keyed on the STORAGE dtype of the pages (int8 for a quantized
    # pool, FLAGS_kv_quant) — an int8 pool reusing an fp32-picked page
    # size would silently lose the VMEM-fit reasoning the measured
    # entry encoded (a quarter the bytes per page changes the winner),
    # so each storage dtype autotunes and validates independently
    return f"paged:{max_len}x{d}:{jnp.dtype(dtype).name}"


def cached_page_size(max_len, d, dtype):
    """Measured page size for this (max_len, head_dim, dtype) from the
    shared flash autotune cache (tools/bench_decode.py commits winners),
    or None.  Entries must survive `pick_page_size`'s floor/tiling rules
    — the same validation discipline as flash_attention.cached_blocks."""
    ent = fa._load_autotune().get(_paged_key(max_len, d, dtype))
    try:
        ps = int(ent[0]) if isinstance(ent, (list, tuple)) else int(ent)
    except (TypeError, ValueError, IndexError):
        return None
    if pick_page_size(max_len, ps) != ps:
        return None
    return ps


def default_page_size(max_len, d, dtype=jnp.float32):
    """The page size the serving pool uses when the caller doesn't pick:
    measured winner if cached, else the shrink default."""
    return cached_page_size(max_len, d, dtype) or \
        pick_page_size(max_len) or _MIN_PAGE_SIZE


# ---------------------------------------------------------------------------
# Write-capped K/V scatter coordinates (shared by the serving engine's
# mixed prefill+decode step and the speculative verify step)
# ---------------------------------------------------------------------------
def paged_write_indices(block_tables, seq_lens, write_caps, qn,
                        num_pages_total, page):
    """Batched scatter coordinates for writing up to ``qn`` new K/V rows
    per sequence into its pages: row ``i`` of sequence ``b`` lands at
    absolute position ``seq_lens[b] + i``.

    block_tables: [B, pages_max] int32; seq_lens: [B] int32 (KV rows
    already valid); write_caps: [B] int32 in [0, qn] — rows
    ``i >= write_caps[b]`` (padding past a prompt chunk / verify window,
    or an inactive slot with cap 0) get page index ``num_pages_total``,
    one past the pool, so an ``.at[...].set`` scatter drops them.

    Returns ``(page_idx, slot)``, both [B, qn] int32: the page id and
    the within-page offset of every row.
    """
    b = block_tables.shape[0]
    pages_max = block_tables.shape[1]
    offs = jnp.arange(qn, dtype=jnp.int32)
    pos = seq_lens[:, None] + offs[None, :]              # [B, qn]
    writable = offs[None, :] < write_caps[:, None]
    # capped rows may sit past the block table's horizon: clamp the
    # LOOKUP index (the row is dropped via the OOB page id anyway)
    bt_idx = jnp.minimum(pos // page, pages_max - 1)
    page_idx = jnp.where(
        writable, block_tables[jnp.arange(b)[:, None], bt_idx],
        num_pages_total)
    return page_idx, pos % page


# ---------------------------------------------------------------------------
# Quantized KV pages (FLAGS_kv_quant=int8): symmetric per-page,
# per-head int8 with the quantization.int8 convention (q_max 127,
# scale = absmax / 127, dequant = q * scale).
# ---------------------------------------------------------------------------
Q_MAX = 127.0  # quantization.int8.Q_MAX (kept local: no layer imports)


def paged_write_spans(block_tables, seq_lens, write_caps, qn,
                      num_pages_total, page):
    """The DISTINCT pages a write of up to ``qn`` rows per sequence
    (rows ``i < write_caps[b]`` at positions ``seq_lens[b] + i``)
    touches — the deduplicated refold set for `paged_quant_write`.
    Rows within one page share a scale, so refolding once per (seq,
    span page) instead of once per row cuts the refold gather traffic
    by up to ``page``x (the difference between the quantized mixed
    step paying ~the attention gather's bandwidth and paying 8x it).

    Returns [B * n_span] int32 page ids with ``num_pages_total`` (one
    past the pool — dropped by scatters, clamped by gathers) for
    inactive sequences and span slots past the write's last page;
    ``n_span = (qn + page - 2) // page + 1`` is the static worst case
    (an unaligned ``qn``-row run)."""
    b = block_tables.shape[0]
    pages_max = block_tables.shape[1]
    n_span = (qn + page - 2) // page + 1
    j = jnp.arange(n_span, dtype=jnp.int32)
    first = seq_lens // page                                  # [B]
    last = (seq_lens + jnp.maximum(write_caps, 1) - 1) // page
    valid = (write_caps[:, None] > 0) & \
        (first[:, None] + j[None, :] <= last[:, None])
    bt_idx = jnp.minimum(first[:, None] + j[None, :], pages_max - 1)
    span = jnp.where(
        valid, block_tables[jnp.arange(b)[:, None], bt_idx],
        num_pages_total)
    return span.reshape(-1)


def paged_quant_write(pages, scales, li, vals, page_idx, slot,
                      span_idx=None):
    """Quantized in-place write of new K/V rows into layer ``li``'s int8
    pages, folding the rows' per-head absmax into the running page
    scales and RE-QUANTIZING a written page's existing rows when its
    scale grows (the "refold" — pages stay self-consistent under one
    scale no matter how incrementally decode/prefill filled them).

    pages: [L, Hkv, P, page, D] int8 (donated by the caller's jit);
    scales: [L, Hkv, P] f32 running page scales (absmax / Q_MAX; 0 =
    never written since (re)allocation — the engine zeroes a page's
    scale entry when the allocator hands it out, so a recycled page's
    stale scale can never leak into a new owner's quantization);
    vals: [R, Hkv, D] new K or V rows (float); page_idx / slot: [R]
    int32 write coordinates — page index P (one past the pool) drops
    the row, exactly like the unquantized scatter sites; span_idx:
    the DEDUPLICATED page set of this write (`paged_write_spans`) the
    refold gathers/scatters over — defaults to ``page_idx`` (per-row:
    bit-identical result, up to ``page``x redundant page traffic).
    Multi-row writers (prefill/mixed/verify) pass the span form;
    single-row-per-sequence decode keeps the default, where per-row
    IS the deduplicated set.

    Returns ``(pages, scales, refolds)`` where ``refolds`` is the
    number of (page, head) scale entries that grew past a previously
    established value (each one re-quantized that page's rows).

    Determinism: the scale fold is a scatter-``max`` (order-free under
    duplicate rows), refold multiplies by ``s_old / s_new`` (exactly
    1.0 for untouched entries, so ``round`` returns the stored int8
    unchanged), and a fresh page (scale 0) deterministically zeroes
    whatever stale rows the recycled buffer held."""
    num_pages = pages.shape[2]
    if span_idx is None:
        span_idx = page_idx
    s_old = scales[li]                                   # [H, P]
    amax = jnp.max(jnp.abs(vals.astype(jnp.float32)), axis=-1)  # [R, H]
    # scatter-max the new rows' absmax/Q_MAX into a P+1 buffer whose
    # extra column absorbs dropped rows (page index P)
    ext = jnp.pad(s_old, ((0, 0), (0, 1)))               # [H, P+1]
    ext = ext.at[:, page_idx].max(amax.T / Q_MAX)
    s_new = ext[:, :num_pages]
    # refold: requantize the written pages' existing rows at the grown
    # scale.  ratio == s_old/s_new <= 1 (scales only grow within an
    # allocation), == 0 for a fresh page (wipes recycled garbage to
    # deterministic zeros), == 1.0 where nothing grew (bit no-op).
    gidx = jnp.minimum(span_idx, num_pages - 1)          # in-bounds gather
    so_g = s_old[:, gidx]                                # [H, S]
    sn_g = s_new[:, gidx]
    ratio = jnp.where(so_g > 0, so_g / jnp.where(sn_g > 0, sn_g, 1.0),
                      0.0)
    old = pages[li][:, gidx].astype(jnp.float32)         # [H, S, page, D]
    requant = jnp.round(old * ratio[..., None, None]).astype(jnp.int8)
    # advanced group (li, span_idx) leads: update shape [S, H, page, D];
    # OOB page index P drops the row, duplicates write identical bytes
    pages = pages.at[li, :, span_idx].set(
        requant.transpose(1, 0, 2, 3))
    # quantize the new rows at their page's (possibly grown) scale and
    # scatter them over the refolded content
    sn_rows = s_new[:, jnp.minimum(page_idx, num_pages - 1)]  # [H, R]
    qrows = jnp.clip(
        jnp.round(vals.astype(jnp.float32)
                  / jnp.maximum(sn_rows.T[..., None], 1e-30)),
        -Q_MAX, Q_MAX).astype(jnp.int8)                  # [R, H, D]
    pages = pages.at[li, :, page_idx, slot, :].set(qrows)
    refolds = jnp.sum((s_new > s_old) & (s_old > 0)).astype(jnp.int32)
    scales = scales.at[li].set(s_new)
    return pages, scales, refolds


# ---------------------------------------------------------------------------
# XLA reference — CPU path and parity ground truth
# ---------------------------------------------------------------------------
def _xla_paged_attention(q, k_pages, v_pages, block_tables, seq_lens,
                         scale=None, q_offsets=None, k_scales=None,
                         v_scales=None):
    """q: [B, Hq, D] (single query token) or [B, Q, Hq, D] (multi-query
    with per-sequence causal offset); k_pages/v_pages:
    [Hkv, num_pages, page, D]; block_tables: [B, pages_max] int32;
    seq_lens: [B] int32 (valid KV tokens per sequence; 0 = inactive slot
    -> zero output); q_offsets: [B] int32 absolute position of query row
    0 (default ``seq_lens - Q``: the queries are the newest tokens);
    k_scales/v_scales: [Hkv, num_pages] f32 per-page, per-head dequant
    scales when the pages are int8 (FLAGS_kv_quant) — the gathered
    pages dequantize (``q8 * scale``) before the identical attention
    math, mirroring the Pallas kernel's in-register dequant exactly.
    Returns the same rank as ``q``.

    Mirrors _sdpa_reference's numerics: logits scaled in the input dtype,
    masked + softmaxed in f32, probs cast back — a sequence's output is
    bit-identical to dense attention over its first ``seq_len`` tokens
    (bottom-right-aligned causal for the multi-query form).
    """
    from ...nn.functional.attention import multi_query_causal_mask

    squeeze = q.ndim == 3
    if squeeze:
        q = q[:, None]
    hkv, _, page, d = k_pages.shape
    b, qn, hq, _ = q.shape
    g = hq // hkv
    if q_offsets is None:
        q_offsets = seq_lens - qn
    s = scale if scale is not None else 1.0 / jnp.sqrt(d).astype(q.dtype)
    # gather each sequence's pages: [Hkv, B, pages_max, page, D]
    k = k_pages[:, block_tables]
    v = v_pages[:, block_tables]
    if k_scales is not None:
        # fused dequant: one multiply per gathered page element, in f32
        # (same product order as the Pallas kernel's per-tile dequant),
        # cast back to the query dtype for the shared cast discipline
        ks = k_scales[:, block_tables][..., None, None]
        vs = v_scales[:, block_tables][..., None, None]
        k = (k.astype(jnp.float32) * ks).astype(q.dtype)
        v = (v.astype(jnp.float32) * vs).astype(q.dtype)
    k = jnp.moveaxis(k, 1, 0).reshape(b, hkv, -1, d)
    v = jnp.moveaxis(v, 1, 0).reshape(b, hkv, -1, d)
    qg = q.reshape(b, qn, hkv, g, d)
    logits = jnp.einsum("bqhgd,bhsd->bqhgs", qg, k) * s
    logits = logits.astype(jnp.float32)
    # [B, Q, S]: kv pos p visible to query i iff p < min(len, off + i + 1)
    valid = multi_query_causal_mask(q_offsets, qn, seq_lens, k.shape[2])
    logits = jnp.where(valid[:, :, None, None, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    # a fully-masked row (seq_len == 0) softmaxes to uniform; zero it so
    # inactive slots emit exact zeros instead of the page-pool mean
    probs = jnp.where(valid[:, :, None, None, :], probs,
                      jnp.zeros((), probs.dtype))
    out = jnp.einsum("bqhgs,bhsd->bqhgd", probs, v)
    out = out.reshape(b, qn, hq, d)
    return out[:, 0] if squeeze else out


# ---------------------------------------------------------------------------
# Pallas decode kernel
# ---------------------------------------------------------------------------
def _decode_kernel(bt_ref, sl_ref, qo_ref, q_ref, k_ref, v_ref, o_ref,
                   acc, m_scr, l_scr, *, page, pages_max, scale, group,
                   q_len):
    # grid (b, h_kv, p): one KV page streams through VMEM per step while
    # the (b, h)-pinned query tile and f32 softmax state stay resident.
    # Query rows are (query_token, gqa_group) pairs: row r is query
    # token r // group, at absolute position qo + r // group, so each
    # row carries its own causal limit (a per-row ragged mask instead of
    # the single-token `pos < sl`).
    b = pl.program_id(0)
    p = pl.program_id(2)
    sl = sl_ref[b]
    qo = qo_ref[b]

    @pl.when(p == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)
        m_scr[...] = jnp.full_like(m_scr, -1e30)
        l_scr[...] = jnp.zeros_like(l_scr)

    @pl.when(p * page < sl)
    def _compute():
        q = q_ref[...].astype(jnp.float32) * scale    # [rows, d]
        k = k_ref[...].astype(jnp.float32)            # [page, d]
        v = v_ref[...].astype(jnp.float32)
        rows = q_ref.shape[0]
        m = m_scr[...][:, 0]
        l = l_scr[...][:, 0]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [rows, page]
        # per-row causal limit: row r sees kv pos < min(sl, qo + qi + 1)
        # (padded rows clamp to the last real query so their reads stay
        # inside the live range; their output is sliced away anyway)
        row_q = jnp.minimum(
            jax.lax.broadcasted_iota(jnp.int32, (rows, page), 0) // group,
            q_len - 1)
        pos = p * page + jax.lax.broadcasted_iota(
            jnp.int32, (rows, page), 1)
        masked = pos < jnp.minimum(sl, qo + row_q + 1)
        logits = jnp.where(masked, logits, -1e30)
        m_blk = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        pr = jnp.exp(logits - m_new[:, None])
        # a row fully masked on this page (early query, late page) has
        # m_new == -1e30 and exp(0) == 1 everywhere: zero it explicitly
        pr = jnp.where(masked, pr, 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(pr, axis=-1)
        acc[...] = acc[...] * alpha[:, None] + jax.lax.dot_general(
            pr, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    @pl.when(p == pages_max - 1)
    def _flush():
        l = l_scr[...][:, 0]
        o_ref[...] = (acc[...] / jnp.maximum(l, 1e-30)[:, None]
                      ).astype(o_ref.dtype)


def _decode_kernel_q(bt_ref, sl_ref, qo_ref, ks_ref, vs_ref, q_ref,
                     k_ref, v_ref, o_ref, acc, m_scr, l_scr, *, page,
                     pages_max, scale, group, q_len):
    # Quantized twin of `_decode_kernel`: the K/V page tiles stream in
    # as int8 and dequantize IN-REGISTER right after the DMA — the
    # scale rows ride the scalar-prefetch channel with the block
    # tables, so the per-page scale lookup is an SMEM read, never a
    # second HBM stream.  Everything after the dequant multiply is the
    # unquantized kernel verbatim (the f32 online-softmax state and
    # masking are identical), which is what keeps the two paths'
    # numerics aligned with the XLA reference's dequant-then-attend.
    b = pl.program_id(0)
    h = pl.program_id(1)
    p = pl.program_id(2)
    sl = sl_ref[b]
    qo = qo_ref[b]
    live = jnp.maximum((sl + page - 1) // page, 1)
    pid = bt_ref[b, jnp.minimum(p, live - 1)]  # the streamed page's id

    @pl.when(p == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)
        m_scr[...] = jnp.full_like(m_scr, -1e30)
        l_scr[...] = jnp.zeros_like(l_scr)

    @pl.when(p * page < sl)
    def _compute():
        q = q_ref[...].astype(jnp.float32) * scale    # [rows, d]
        # dequant in-register, then round-trip through the QUERY dtype
        # exactly like the XLA reference's `.astype(q.dtype)` — for
        # sub-f32 models (bf16) the cast is lossy, and skipping it here
        # would make the two backends attend over different K/V values
        # (a no-op for f32, where the tests pin bit-identical operands)
        k = (k_ref[...].astype(jnp.float32) * ks_ref[h, pid]
             ).astype(q_ref.dtype).astype(jnp.float32)
        v = (v_ref[...].astype(jnp.float32) * vs_ref[h, pid]
             ).astype(q_ref.dtype).astype(jnp.float32)
        rows = q_ref.shape[0]
        m = m_scr[...][:, 0]
        l = l_scr[...][:, 0]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [rows, page]
        row_q = jnp.minimum(
            jax.lax.broadcasted_iota(jnp.int32, (rows, page), 0) // group,
            q_len - 1)
        pos = p * page + jax.lax.broadcasted_iota(
            jnp.int32, (rows, page), 1)
        masked = pos < jnp.minimum(sl, qo + row_q + 1)
        logits = jnp.where(masked, logits, -1e30)
        m_blk = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        pr = jnp.exp(logits - m_new[:, None])
        pr = jnp.where(masked, pr, 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(pr, axis=-1)
        acc[...] = acc[...] * alpha[:, None] + jax.lax.dot_general(
            pr, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    @pl.when(p == pages_max - 1)
    def _flush():
        l = l_scr[...][:, 0]
        o_ref[...] = (acc[...] / jnp.maximum(l, 1e-30)[:, None]
                      ).astype(o_ref.dtype)


def _pallas_paged_attention(q, k_pages, v_pages, block_tables, seq_lens,
                            scale=None, q_offsets=None, k_scales=None,
                            v_scales=None):
    hkv, num_pages, page, d = k_pages.shape
    squeeze = q.ndim == 3
    if squeeze:
        q = q[:, None]
    b, qn, hq, _ = q.shape
    g = hq // hkv
    if q_offsets is None:
        q_offsets = seq_lens - qn
    # rows = (query token, gqa group) pairs, padded up to the f32
    # sublane tile so the [rows, d] blocks map onto the VPU/MXU
    rows = qn * g
    gp = -(-rows // _MIN_GROUP_ROWS) * _MIN_GROUP_ROWS
    s = scale if scale is not None else 1.0 / math.sqrt(d)

    qg = q.reshape(b, qn, hkv, g, d).transpose(0, 2, 1, 3, 4)
    qg = qg.reshape(b, hkv, rows, d)
    if gp != rows:
        # pad the query rows up to the sublane tile; padded rows compute
        # garbage that is sliced away after the call
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - rows), (0, 0)))
    pages_max = block_tables.shape[1]
    block_tables = block_tables.astype(jnp.int32)
    seq_lens = seq_lens.astype(jnp.int32)
    q_offsets = q_offsets.astype(jnp.int32)
    quant = k_scales is not None
    n_prefetch = 5 if quant else 3

    def q_map(bi, h, p, *pref):
        return (bi, h, 0, 0)

    def kv_map(bi, h, p, bt, sl, *pref):
        # dead pages clamp to the last live page: the repeated index
        # skips the DMA (flash_attention's dead-block clamp, paged form).
        # max(live, 1) keeps a zero-length slot pointing at a real page.
        live = jnp.maximum((sl[bi] + page - 1) // page, 1)
        return (h, bt[bi, jnp.minimum(p, live - 1)], 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_prefetch,
        grid=(b, hkv, pages_max),
        in_specs=[
            pl.BlockSpec((None, None, gp, d), q_map),
            pl.BlockSpec((None, None, page, d), kv_map),
            pl.BlockSpec((None, None, page, d), kv_map),
        ],
        out_specs=pl.BlockSpec((None, None, gp, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((gp, d), jnp.float32),
            pltpu.VMEM((gp, _LANES), jnp.float32),
            pltpu.VMEM((gp, _LANES), jnp.float32),
        ],
    )
    kernel = _decode_kernel_q if quant else _decode_kernel
    operands = (block_tables, seq_lens, q_offsets)
    if quant:
        # the scale rows ride the scalar-prefetch channel (SMEM) with
        # the block tables: the kernel's per-page dequant lookup is
        # ks[h, bt[b, p]], the same indirection the DMA maps use
        operands = operands + (k_scales.astype(jnp.float32),
                               v_scales.astype(jnp.float32))
    out = pl.pallas_call(
        functools.partial(kernel, page=page, pages_max=pages_max,
                          scale=s, group=g, q_len=qn),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, gp, d), q.dtype),
    )(*operands, qg, k_pages, v_pages)
    out = out[:, :, :rows, :].reshape(b, hkv, qn, g, d)
    out = out.transpose(0, 2, 1, 3, 4).reshape(b, qn, hq, d)
    return out[:, 0] if squeeze else out


# ---------------------------------------------------------------------------
# public entry point
# ---------------------------------------------------------------------------
def paged_attention(q, k_pages, v_pages, block_tables, seq_lens,
                    scale=None, q_offsets=None, k_scales=None,
                    v_scales=None):
    """Decode-step attention over a paged KV cache.

    q: [B, Hq, D] (one query token per sequence) or [B, Q, Hq, D]
    (ragged multi-query: Q query tokens per sequence, each at absolute
    position ``q_offsets[b] + i`` — the speculative-decode verify /
    chunked-prefill form);
    k_pages/v_pages: [Hkv, num_pages, page_size, D];
    block_tables: [B, pages_max] int32 page ids in position order;
    seq_lens: [B] int32 valid KV tokens per sequence (0 = inactive slot);
    q_offsets: [B] int32 position of each sequence's first query row
    (default ``seq_lens - Q``: the queries are the newest tokens);
    k_scales/v_scales: [Hkv, num_pages] f32 per-page, per-head dequant
    scales, REQUIRED when the pages are int8 (FLAGS_kv_quant) —
    dequantization fuses into the K/V loads of whichever backend runs.

    Hq must be a multiple of Hkv (grouped-query attention).  Uses the
    Pallas kernel on TPU (FLAGS_use_pallas_attention '1'/'auto'; '0'
    forces the reference), the XLA reference elsewhere.
    """
    hkv, num_pages, page, d = k_pages.shape
    if q.ndim not in (3, 4):
        raise ValueError(f"q must be [B, Hq, D] or [B, Q, Hq, D], "
                         f"got rank {q.ndim}")
    hq, dq = q.shape[-2], q.shape[-1]
    if hq % hkv:
        raise ValueError(
            f"query heads {hq} not a multiple of kv heads {hkv}")
    if dq != d:
        raise ValueError(f"head_dim mismatch: q {dq} vs pages {d}")
    if jnp.dtype(k_pages.dtype) == jnp.int8:
        if k_scales is None or v_scales is None:
            raise ValueError(
                "int8 KV pages need k_scales/v_scales ([Hkv, num_pages]"
                " f32 per-page dequant scales)")
        if tuple(k_scales.shape) != (hkv, num_pages):
            raise ValueError(
                f"k_scales shape {tuple(k_scales.shape)} != "
                f"(Hkv, num_pages) = {(hkv, num_pages)}")
        if tuple(v_scales.shape) != (hkv, num_pages):
            # same check for V: a stale/mis-sized scale array would
            # otherwise mis-dequantize silently via clamped gathers
            raise ValueError(
                f"v_scales shape {tuple(v_scales.shape)} != "
                f"(Hkv, num_pages) = {(hkv, num_pages)}")
    elif k_scales is not None or v_scales is not None:
        raise ValueError(
            "k_scales/v_scales passed for non-int8 KV pages "
            f"(dtype {k_pages.dtype})")
    if _paged_kernel_wanted():
        return _pallas_paged_attention(q, k_pages, v_pages, block_tables,
                                       seq_lens, scale, q_offsets,
                                       k_scales, v_scales)
    return _xla_paged_attention(q, k_pages, v_pages, block_tables,
                                seq_lens, scale, q_offsets,
                                k_scales, v_scales)


def _paged_kernel_wanted() -> bool:
    # decode over pages has no composed-XLA crossover to respect (the
    # gather alone re-materializes the whole cache), so 'auto' means ON;
    # '0' still forces the reference for debugging
    from ...core import flags as _flags

    if not _HAS_PALLAS or jax.default_backend() != "tpu":
        return False
    try:
        pol = str(_flags.flag("use_pallas_attention"))
    except Exception:
        return False
    return pol in ("1", "True", "true", "auto")
