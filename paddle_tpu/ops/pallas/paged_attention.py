"""Ragged paged-attention decode kernel (TPU Pallas) + XLA reference.

The serving-side complement of flash_attention.py: decode-step attention
over a paged KV cache ("Ragged Paged Attention", PAPERS.md).  K/V live in
HBM as fixed-size pages indexed by a per-sequence block table; each
sequence in the batch has its own length (ragged batch), and query heads
may outnumber KV heads (grouped-query attention).

Kernel shape (the TPU paged-decode idiom):

* grid ``(batch, kv_heads, pages_max)`` with the page axis fastest;
* the block table and sequence lengths ride in as **scalar-prefetch**
  operands (`pltpu.PrefetchScalarGridSpec`) so the K/V BlockSpec index
  maps can translate the streamed page number through the block table —
  the gather indirection happens in the DMA engine, not in compute;
* pages past a sequence's live range clamp their fetch index to the last
  live page (Pallas skips the re-fetch when the index repeats — same
  dead-block trick as flash_attention's causal clamps) and gate compute
  off with ``pl.when``;
* per-(batch, kv-head) online-softmax state (f32 acc / running max /
  running sum) stays resident in VMEM scratch across the page stream, so
  VMEM usage is constant in sequence length.

The XLA reference (`_xla_paged_attention`) is the numerics ground truth
and the CPU path; it mirrors `nn.functional.attention._sdpa_reference`'s
cast discipline exactly (scale in input dtype, f32 softmax) so the paged
engine bit-matches the eager concat-cache decode path.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from . import flash_attention as fa

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

_LANES = 128
# minimum query-group rows per compute tile: pad the GQA group dim up to
# the f32 sublane tile (8) so the [group, head_dim] blocks map onto the
# VPU/MXU without sub-tile layouts
_MIN_GROUP_ROWS = 8
# page floor: below 16 tokens the per-page DMA descriptor overhead beats
# the payload (the page-axis analog of pick_blocks' 128 floor)
_MIN_PAGE_SIZE = 16
_DEFAULT_PAGE_SIZE = 64


# ---------------------------------------------------------------------------
# Page-size selection — the pick_blocks/cached_blocks machinery from
# flash_attention applied to the page axis: explicit caller values win,
# then a measured winner from the shared autotune cache (validated through
# the same shrink rules so a stale/hand-edited entry degrades instead of
# crashing the pool constructor), then the power-of-two shrink default.
# ---------------------------------------------------------------------------
def pick_page_size(max_len: int, page_size: int = _DEFAULT_PAGE_SIZE):
    """Largest power-of-two page (floor _MIN_PAGE_SIZE) that tiles
    ``max_len`` — pick_blocks' shrink rule on the page axis; None when no
    page size tiles the budget."""
    while page_size > _MIN_PAGE_SIZE and max_len % page_size:
        page_size //= 2
    if page_size < _MIN_PAGE_SIZE or max_len % page_size:
        return None
    return page_size


def _paged_key(max_len, d, dtype):
    return f"paged:{max_len}x{d}:{jnp.dtype(dtype).name}"


def cached_page_size(max_len, d, dtype):
    """Measured page size for this (max_len, head_dim, dtype) from the
    shared flash autotune cache (tools/bench_decode.py commits winners),
    or None.  Entries must survive `pick_page_size`'s floor/tiling rules
    — the same validation discipline as flash_attention.cached_blocks."""
    ent = fa._load_autotune().get(_paged_key(max_len, d, dtype))
    try:
        ps = int(ent[0]) if isinstance(ent, (list, tuple)) else int(ent)
    except (TypeError, ValueError, IndexError):
        return None
    if pick_page_size(max_len, ps) != ps:
        return None
    return ps


def default_page_size(max_len, d, dtype=jnp.float32):
    """The page size the serving pool uses when the caller doesn't pick:
    measured winner if cached, else the shrink default."""
    return cached_page_size(max_len, d, dtype) or \
        pick_page_size(max_len) or _MIN_PAGE_SIZE


# ---------------------------------------------------------------------------
# XLA reference — CPU path and parity ground truth
# ---------------------------------------------------------------------------
def _xla_paged_attention(q, k_pages, v_pages, block_tables, seq_lens,
                         scale=None):
    """q: [B, Hq, D]; k_pages/v_pages: [Hkv, num_pages, page, D];
    block_tables: [B, pages_max] int32; seq_lens: [B] int32 (valid KV
    tokens per sequence; 0 = inactive slot -> zero output).
    Returns [B, Hq, D].

    Mirrors _sdpa_reference's numerics: logits scaled in the input dtype,
    masked + softmaxed in f32, probs cast back — a sequence's output is
    bit-identical to dense attention over its first ``seq_len`` tokens.
    """
    hkv, _, page, d = k_pages.shape
    b, hq, _ = q.shape
    g = hq // hkv
    s = scale if scale is not None else 1.0 / jnp.sqrt(d).astype(q.dtype)
    # gather each sequence's pages: [Hkv, B, pages_max, page, D]
    k = k_pages[:, block_tables]
    v = v_pages[:, block_tables]
    k = jnp.moveaxis(k, 1, 0).reshape(b, hkv, -1, d)
    v = jnp.moveaxis(v, 1, 0).reshape(b, hkv, -1, d)
    qg = q.reshape(b, hkv, g, d)
    logits = jnp.einsum("bhgd,bhsd->bhgs", qg, k) * s
    logits = logits.astype(jnp.float32)
    pos = jnp.arange(k.shape[2], dtype=jnp.int32)
    valid = pos[None, :] < seq_lens[:, None]  # [B, S]
    logits = jnp.where(valid[:, None, None, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    # a fully-masked row (seq_len == 0) softmaxes to uniform; zero it so
    # inactive slots emit exact zeros instead of the page-pool mean
    probs = jnp.where(valid[:, None, None, :], probs,
                      jnp.zeros((), probs.dtype))
    out = jnp.einsum("bhgs,bhsd->bhgd", probs, v)
    return out.reshape(b, hq, d)


# ---------------------------------------------------------------------------
# Pallas decode kernel
# ---------------------------------------------------------------------------
def _decode_kernel(bt_ref, sl_ref, q_ref, k_ref, v_ref, o_ref,
                   acc, m_scr, l_scr, *, page, pages_max, scale):
    # grid (b, h_kv, p): one KV page streams through VMEM per step while
    # the (b, h)-pinned query tile and f32 softmax state stay resident
    b = pl.program_id(0)
    p = pl.program_id(2)
    sl = sl_ref[b]

    @pl.when(p == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)
        m_scr[...] = jnp.full_like(m_scr, -1e30)
        l_scr[...] = jnp.zeros_like(l_scr)

    @pl.when(p * page < sl)
    def _compute():
        q = q_ref[...].astype(jnp.float32) * scale    # [gp, d]
        k = k_ref[...].astype(jnp.float32)            # [page, d]
        v = v_ref[...].astype(jnp.float32)
        m = m_scr[...][:, 0]
        l = l_scr[...][:, 0]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [gp, page]
        pos = p * page + jax.lax.broadcasted_iota(
            jnp.int32, (1, page), 1)[0]
        logits = jnp.where((pos < sl)[None, :], logits, -1e30)
        m_blk = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        pr = jnp.exp(logits - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(pr, axis=-1)
        acc[...] = acc[...] * alpha[:, None] + jax.lax.dot_general(
            pr, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    @pl.when(p == pages_max - 1)
    def _flush():
        l = l_scr[...][:, 0]
        o_ref[...] = (acc[...] / jnp.maximum(l, 1e-30)[:, None]
                      ).astype(o_ref.dtype)


def _pallas_paged_attention(q, k_pages, v_pages, block_tables, seq_lens,
                            scale=None):
    hkv, num_pages, page, d = k_pages.shape
    b, hq, _ = q.shape
    g = hq // hkv
    gp = max(_MIN_GROUP_ROWS, g)
    s = scale if scale is not None else 1.0 / math.sqrt(d)

    qg = q.reshape(b, hkv, g, d)
    if gp != g:
        # pad the query-group rows up to the sublane tile; padded rows
        # compute garbage that is sliced away after the call
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - g), (0, 0)))
    pages_max = block_tables.shape[1]
    block_tables = block_tables.astype(jnp.int32)
    seq_lens = seq_lens.astype(jnp.int32)

    def q_map(bi, h, p, bt, sl):
        return (bi, h, 0, 0)

    def kv_map(bi, h, p, bt, sl):
        # dead pages clamp to the last live page: the repeated index
        # skips the DMA (flash_attention's dead-block clamp, paged form).
        # max(live, 1) keeps a zero-length slot pointing at a real page.
        live = jnp.maximum((sl[bi] + page - 1) // page, 1)
        return (h, bt[bi, jnp.minimum(p, live - 1)], 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hkv, pages_max),
        in_specs=[
            pl.BlockSpec((None, None, gp, d), q_map),
            pl.BlockSpec((None, None, page, d), kv_map),
            pl.BlockSpec((None, None, page, d), kv_map),
        ],
        out_specs=pl.BlockSpec((None, None, gp, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((gp, d), jnp.float32),
            pltpu.VMEM((gp, _LANES), jnp.float32),
            pltpu.VMEM((gp, _LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, page=page, pages_max=pages_max,
                          scale=s),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, gp, d), q.dtype),
    )(block_tables, seq_lens, qg, k_pages, v_pages)
    return out[:, :, :g, :].reshape(b, hq, d)


# ---------------------------------------------------------------------------
# public entry point
# ---------------------------------------------------------------------------
def paged_attention(q, k_pages, v_pages, block_tables, seq_lens,
                    scale=None):
    """Decode-step attention over a paged KV cache.

    q: [B, Hq, D] (one query token per sequence);
    k_pages/v_pages: [Hkv, num_pages, page_size, D];
    block_tables: [B, pages_max] int32 page ids in position order;
    seq_lens: [B] int32 valid KV tokens per sequence (0 = inactive slot).

    Hq must be a multiple of Hkv (grouped-query attention).  Uses the
    Pallas kernel on TPU (FLAGS_use_pallas_attention '1'/'auto'; '0'
    forces the reference), the XLA reference elsewhere.
    """
    hkv, _, page, d = k_pages.shape
    b, hq, dq = q.shape
    if hq % hkv:
        raise ValueError(
            f"query heads {hq} not a multiple of kv heads {hkv}")
    if dq != d:
        raise ValueError(f"head_dim mismatch: q {dq} vs pages {d}")
    if _paged_kernel_wanted():
        return _pallas_paged_attention(q, k_pages, v_pages, block_tables,
                                       seq_lens, scale)
    return _xla_paged_attention(q, k_pages, v_pages, block_tables,
                                seq_lens, scale)


def _paged_kernel_wanted() -> bool:
    # decode over pages has no composed-XLA crossover to respect (the
    # gather alone re-materializes the whole cache), so 'auto' means ON;
    # '0' still forces the reference for debugging
    from ...core import flags as _flags

    if not _HAS_PALLAS or jax.default_backend() != "tpu":
        return False
    try:
        pol = str(_flags.flag("use_pallas_attention"))
    except Exception:
        return False
    return pol in ("1", "True", "true", "auto")
