"""Flash attention Pallas kernel (TPU).

Replaces the reference's fused inference attention
(`operators/fused/multihead_matmul_op.cu`) and the composed
matmul+softmax+matmul training path with a tiled online-softmax kernel that
keeps the running statistics in VMEM (per /opt/skills/guides/pallas_guide.md).
Falls back to the XLA composed form when shapes don't fit the tile grid.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


def _xla_reference(q, k, v, mask, is_causal, scale):
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * s
    logits = logits.astype(jnp.float32)
    if is_causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        causal = jnp.tril(jnp.ones((ql, kl), dtype=bool), k=kl - ql)
        logits = jnp.where(causal, logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, seq_k, scale,
                 causal, block_q, q_offset_grid):
    # grid: (batch*heads, num_q_blocks); process all K blocks in a loop
    q = q_ref[...].astype(jnp.float32) * scale  # [block_q, d]
    m = jnp.full((block_q,), -1e30, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    qi = pl.program_id(1)
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)[:, 0]

    num_k = seq_k // block_k

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        if causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)[0]
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask, logits, -1e30)
        m_blk = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(logits - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(0, num_k, body, (m, l, acc))
    o_ref[...] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def flash_attention_fwd(q, k, v, mask=None, is_causal=False, scale=None,
                        block_q=256, block_k=256):
    """q,k,v: [B,H,S,D].  Uses the Pallas kernel when mask is None and shapes
    tile; otherwise the XLA composed reference.  Differentiable: the
    backward pass recomputes attention in the composed XLA form (the
    flash-attention recompute strategy — no S^2 tensor is saved)."""
    if (not _HAS_PALLAS or mask is not None
            or q.shape[-2] % block_q or k.shape[-2] % block_k
            or jax.default_backend() != "tpu"):
        return _xla_reference(q, k, v, mask, is_causal, scale)
    # Policy (measured on v5e): XLA's fused attention wins at moderate
    # sequence lengths; the tiled kernel wins once the S^2 logits
    # intermediate stops fitting comfortably in HBM/VMEM traffic.  Flag
    # FLAGS_use_pallas_attention: "auto" (default) = kernel at S >= 2048,
    # "1"/"0" force on/off.
    from ...core import flags as _flags

    pol = str(_flags.flag("use_pallas_attention"))
    use = (pol in ("1", "True", "true") or
           (pol == "auto" and q.shape[-2] >= 2048))
    if not use:
        return _xla_reference(q, k, v, mask, is_causal, scale)
    return _flash_diff(q, k, v, is_causal, scale, block_q, block_k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_diff(q, k, v, is_causal, scale, block_q, block_k):
    return _pallas_forward(q, k, v, is_causal, scale, block_q, block_k)


def _flash_diff_fwd(q, k, v, is_causal, scale, block_q, block_k):
    out = _pallas_forward(q, k, v, is_causal, scale, block_q, block_k)
    return out, (q, k, v)


def _flash_diff_bwd(is_causal, scale, block_q, block_k, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _xla_reference(q_, k_, v_, None, is_causal,
                                          scale), q, k, v)
    return vjp(g)


_flash_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


def _pallas_forward(q, k, v, is_causal, scale, block_q, block_k):
    b, h, sq, d = q.shape
    sk = k.shape[-2]
    s = scale if scale is not None else 1.0 / math.sqrt(d)

    qr = q.reshape(b * h, sq, d)
    kr = k.reshape(b * h, sk, d)
    vr = v.reshape(b * h, sk, d)

    kernel = functools.partial(
        _attn_kernel, block_k=block_k, seq_k=sk, scale=s, causal=is_causal,
        block_q=block_q, q_offset_grid=None,
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
    )(qr, kr, vr)
    return out.reshape(b, h, sq, d)
