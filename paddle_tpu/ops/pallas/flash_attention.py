"""Flash attention Pallas kernels (TPU), forward and backward.

Replaces the reference's fused inference attention
(`operators/fused/multihead_matmul_op.cu`) and the composed
matmul+softmax+matmul training path with tiled online-softmax kernels that
keep the running statistics in VMEM (per /opt/skills/guides/pallas_guide.md).

The backward pass is a real pair of Pallas kernels (dq and dk/dv tiles,
recomputing P per tile from the saved logsumexp — no S^2 tensor ever hits
HBM), matching the memory behaviour the flash-attention algorithm promises.
Falls back to the XLA composed form when shapes don't tile or a dense mask
is supplied.
"""
from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

# TPU float32 tiling wants the lane (last) dimension to be 128; the per-row
# softmax statistics are stored broadcast across one lane tile.
_LANES = 128


def _composed_attention(q, k, v, mask, is_causal, scale, want_lse=False):
    """The single composed (O(S^2)) attention definition — the numerics
    ground truth for the Pallas kernels AND the recompute backward of the
    ring flash blocks.  Returns out (q.dtype) or (out, lse f32)."""
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * s
    logits = logits.astype(jnp.float32)
    if is_causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        causal = jnp.tril(jnp.ones((ql, kl), dtype=bool), k=kl - ql)
        logits = jnp.where(causal, logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    if want_lse:
        return out, jax.scipy.special.logsumexp(logits, axis=-1)
    return out


def _xla_reference(q, k, v, mask, is_causal, scale):
    return _composed_attention(q, k, v, mask, is_causal, scale)


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel_resident(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k,
                         seq_k, scale, causal, block_q):
    # grid: (batch*heads, num_q_blocks); whole K/V for the head resident
    # in VMEM, looped over in block_k slices.  Fastest form (no acc
    # scratch traffic, K block count can be clipped under the causal
    # mask), used while 2*seq_k*d fits the VMEM budget; the streaming
    # kernel below takes over beyond it.
    q = q_ref[...].astype(jnp.float32) * scale  # [block_q, d]
    m = jnp.full((block_q,), -1e30, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    qi = pl.program_id(1)
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, 1), 0)[:, 0]

    num_k = seq_k // block_k
    if causal:
        # Only K blocks intersecting the lower triangle contribute.
        num_k = jnp.minimum(num_k,
                            ((qi + 1) * block_q + block_k - 1) // block_k)

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        if causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)[0]
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask, logits, -1e30)
        m_blk = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(logits - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(0, num_k, body, (m, l, acc))
    o_ref[...] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    lse_ref[...] = jnp.broadcast_to(lse[:, None], (block_q, _LANES))


# Causal dead-block fetch clamps, shared by the streaming forward and both
# backward kernels.  A tile wholly above the causal diagonal contributes
# nothing: compute there is pl.when-gated off in the kernels, and these
# index maps additionally skip the DMA by clamping the streamed block
# index to the live range (Pallas skips re-fetch when the index repeats).
# Keep the formulas in sync with the kernels' `live` predicates.
def _stream_idx(i, j, r):
    return (i, r, 0)


def _causal_kv_clamp(block_q, block_k):
    """Fetch index for K/V streamed under a pinned q block j: clamp to the
    last live K block, ((j+1)*block_q - 1) // block_k."""
    def idx(i, j, r):
        return (i, jnp.minimum(r, ((j + 1) * block_q - 1) // block_k), 0)
    return idx


def _causal_q_clamp(block_q, block_k):
    """Fetch index for Q rows streamed under a pinned K block j: clamp to
    the first live q block, (j*block_k) // block_q."""
    def idx(i, j, r):
        return (i, jnp.maximum(r, (j * block_k) // block_q), 0)
    return idx


# VMEM budget for holding a head's full K+V resident in the forward
# kernel (the scoped limit on this toolchain is 16MB; leave room for the
# q/o blocks and pipelining buffers).  Measured: resident beats streaming
# by 5-20% where it fits (S<=8192 at d=64), so both kernels are kept.
_RESIDENT_KV_BYTES = 6 * 1024 * 1024


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_scr, l_scr, *,
                block_k, seq_k, scale, causal, block_q):
    # grid (bh, num_q, num_k): K/V blocks STREAM through VMEM (k is the
    # fastest grid dim) while the (bh, q)-pinned output block and the f32
    # scratch accumulators (acc / running max / running sum) stay resident
    # — constant VMEM at any sequence length, same scheme as the backward
    # kernels (the earlier all-of-K/V-resident form hit the 16MB scoped
    # VMEM limit around S=16k at d=128 bf16; advisor round-2 finding).
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    num_k = seq_k // block_k

    @pl.when(kj == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)
        m_scr[...] = jnp.full_like(m_scr, -1e30)
        l_scr[...] = jnp.zeros_like(l_scr)

    # causal: a K block entirely above the diagonal contributes nothing
    live = ((qi + 1) * block_q - 1 >= kj * block_k) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[...].astype(jnp.float32) * scale  # [block_q, d]
        k_blk = k_ref[...].astype(jnp.float32)      # [block_k, d]
        v_blk = v_ref[...].astype(jnp.float32)
        m = m_scr[...][:, 0]
        l = l_scr[...][:, 0]
        logits = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0)[:, 0]
            k_pos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)[0]
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask, logits, -1e30)
        m_blk = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(logits - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc[...] = acc[...] * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    @pl.when(kj == num_k - 1)
    def _flush():
        m = m_scr[...][:, 0]
        l = l_scr[...][:, 0]
        o_ref[...] = (acc[...] / jnp.maximum(l, 1e-30)[:, None]
                      ).astype(o_ref.dtype)
        lse = m + jnp.log(jnp.maximum(l, 1e-30))
        lse_ref[...] = jnp.broadcast_to(lse[:, None], (block_q, _LANES))


def _pallas_forward(q, k, v, is_causal, scale, block_q, block_k):
    """Returns (out [B,H,Sq,D], lse [B*H, Sq] fp32)."""
    b, h, sq, d = q.shape
    sk = k.shape[-2]
    s = scale if scale is not None else 1.0 / math.sqrt(d)

    qr = q.reshape(b * h, sq, d)
    kr = k.reshape(b * h, sk, d)
    vr = v.reshape(b * h, sk, d)

    if 2 * sk * d * q.dtype.itemsize <= _RESIDENT_KV_BYTES:
        out, lse = pl.pallas_call(
            functools.partial(
                _fwd_kernel_resident, block_k=block_k, seq_k=sk, scale=s,
                causal=is_causal, block_q=block_q),
            grid=(b * h, sq // block_q),
            in_specs=[
                pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
                pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((None, block_q, _LANES),
                             lambda i, j: (i, j, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
                jax.ShapeDtypeStruct((b * h, sq, _LANES), jnp.float32),
            ],
        )(qr, kr, vr)
        return out.reshape(b, h, sq, d), lse[:, :, 0]

    kernel = functools.partial(
        _fwd_kernel, block_k=block_k, seq_k=sk, scale=s, causal=is_causal,
        block_q=block_q,
    )
    kv_idx = (_causal_kv_clamp(block_q, block_k) if is_causal
              else _stream_idx)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, sq // block_q, sk // block_k),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j, r: (i, j, 0)),
            pl.BlockSpec((None, block_k, d), kv_idx),
            pl.BlockSpec((None, block_k, d), kv_idx),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j, r: (i, j, 0)),
            pl.BlockSpec((None, block_q, _LANES),
                         lambda i, j, r: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, _LANES), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),
                        pltpu.VMEM((block_q, _LANES), jnp.float32),
                        pltpu.VMEM((block_q, _LANES), jnp.float32)],
    )(qr, kr, vr)
    return out.reshape(b, h, sq, d), lse[:, :, 0]


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------
#
# Standard flash-attention backward split into two kernels so each output
# tile has a single writer:
#   dkv kernel: grid over K blocks, loops over Q blocks, accumulates
#               dV = P^T dO and dK = dS^T (Q*scale)
#   dq  kernel: grid over Q blocks, loops over K blocks, accumulates
#               dQ = scale * dS K
# with P recomputed per tile from the saved logsumexp and
# dS = P * (dP - delta).  delta = rowsum(dO * O) is computed in-kernel
# from the saved O (cheap VPU reduce) rather than precomputed — passing O
# (input dtype, D lanes) costs 1/8 the HBM traffic of a broadcast f32
# 128-lane delta array.  lse stays in the 128-lane broadcast layout
# (upstream jax's flash kernel convention); the compact
# (sq//128, 128)-packed alternative needs a cross-lane reshape in-kernel,
# which Mosaic fails to lower.


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                    dk_ref, dv_ref, acc_dk, acc_dv, *, block_q, block_k,
                    seq_q, scale, causal):
    # grid (bh, num_k, num_q): the q axis is the FASTEST grid dim, so the
    # (bh, k)-pinned output blocks and f32 scratch accumulators stay
    # resident while q/do/o/lse blocks stream through VMEM — constant VMEM
    # at any sequence length (the all-rows-in-VMEM form topped out ~4k)
    ki = pl.program_id(1)
    qj = pl.program_id(2)
    num_q = seq_q // block_q

    @pl.when(qj == 0)
    def _init():
        acc_dk[...] = jnp.zeros_like(acc_dk)
        acc_dv[...] = jnp.zeros_like(acc_dv)

    # causal: a tile entirely above the diagonal contributes nothing —
    # skip its matmuls (max q_pos < min k_pos)
    live = ((qj + 1) * block_q - 1 >= ki * block_k) if causal else True

    @pl.when(live)
    def _compute():
        k_blk = k_ref[...].astype(jnp.float32)          # [block_k, d]
        v_blk = v_ref[...].astype(jnp.float32)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)[0]
        q_blk = q_ref[...].astype(jnp.float32) * scale  # [block_q, d]
        do_blk = do_ref[...].astype(jnp.float32)
        o_blk = o_ref[...].astype(jnp.float32)
        lse = lse_ref[...][:, 0]
        delta = jnp.sum(do_blk * o_blk, axis=-1)
        logits = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        if causal:
            q_pos = qj * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0)[:, 0]
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask, logits, -1e30)
        p = jnp.exp(logits - lse[:, None])           # [block_q, block_k]
        acc_dv[...] += jax.lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None])
        acc_dk[...] += jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qj == num_q - 1)
    def _flush():
        dk_ref[...] = acc_dk[...].astype(dk_ref.dtype)
        dv_ref[...] = acc_dv[...].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                   dq_ref, acc_dq, *, block_q, block_k, seq_k, scale,
                   causal):
    # grid (bh, num_q, num_k): k blocks stream while the dq accumulator
    # stays pinned (same streaming scheme as the dkv kernel)
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    num_k = seq_k // block_k

    @pl.when(kj == 0)
    def _init():
        acc_dq[...] = jnp.zeros_like(acc_dq)

    live = ((qi + 1) * block_q - 1 >= kj * block_k) if causal else True

    @pl.when(live)
    def _compute():
        q_blk = q_ref[...].astype(jnp.float32) * scale   # [block_q, d]
        do_blk = do_ref[...].astype(jnp.float32)
        lse = lse_ref[...][:, 0]
        delta = jnp.sum(do_blk * o_ref[...].astype(jnp.float32), axis=-1)
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)[:, 0]
        k_blk = k_ref[...].astype(jnp.float32)
        v_blk = v_ref[...].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if causal:
            k_pos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)[0]
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask, logits, -1e30)
        p = jnp.exp(logits - lse[:, None])
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None])
        acc_dq[...] += jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kj == num_k - 1)
    def _flush():
        dq_ref[...] = (acc_dq[...] * scale).astype(dq_ref.dtype)


def _pallas_backward(q, k, v, out, lse, g, is_causal, scale, block_q,
                     block_k):
    b, h, sq, d = q.shape
    sk = k.shape[-2]
    s = scale if scale is not None else 1.0 / math.sqrt(d)

    qr = q.reshape(b * h, sq, d)
    kr = k.reshape(b * h, sk, d)
    vr = v.reshape(b * h, sk, d)
    dor = g.reshape(b * h, sq, d)
    outr = out.reshape(b * h, sq, d)
    lse_b = jnp.broadcast_to(lse[:, :, None], (b * h, sq, _LANES))

    q_idx = (_causal_q_clamp(block_q, block_k) if is_causal
             else _stream_idx)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=block_q, block_k=block_k,
                          seq_q=sq, scale=s, causal=is_causal),
        grid=(b * h, sk // block_k, sq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), q_idx),
            pl.BlockSpec((None, block_k, d), lambda i, j, r: (i, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j, r: (i, j, 0)),
            pl.BlockSpec((None, block_q, d), q_idx),
            pl.BlockSpec((None, block_q, d), q_idx),
            pl.BlockSpec((None, block_q, _LANES), q_idx),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda i, j, r: (i, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j, r: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
    )(qr, kr, vr, dor, outr, lse_b)

    kv_idx = (_causal_kv_clamp(block_q, block_k) if is_causal
              else _stream_idx)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_q=block_q, block_k=block_k,
                          seq_k=sk, scale=s, causal=is_causal),
        grid=(b * h, sq // block_q, sk // block_k),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j, r: (i, j, 0)),
            pl.BlockSpec((None, block_k, d), kv_idx),
            pl.BlockSpec((None, block_k, d), kv_idx),
            pl.BlockSpec((None, block_q, d), lambda i, j, r: (i, j, 0)),
            pl.BlockSpec((None, block_q, d), lambda i, j, r: (i, j, 0)),
            pl.BlockSpec((None, block_q, _LANES),
                         lambda i, j, r: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d),
                               lambda i, j, r: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
    )(qr, kr, vr, dor, outr, lse_b)

    return (dq.reshape(b, h, sq, d), dk.reshape(b, h, sk, d),
            dv.reshape(b, h, sk, d))


# ---------------------------------------------------------------------------
# Differentiable wrapper + public entry point
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_diff(q, k, v, is_causal, scale, block_q, block_k):
    out, _ = _pallas_forward(q, k, v, is_causal, scale, block_q, block_k)
    return out


def _flash_diff_fwd(q, k, v, is_causal, scale, block_q, block_k):
    out, lse = _pallas_forward(q, k, v, is_causal, scale, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_diff_bwd(is_causal, scale, block_q, block_k, res, g):
    q, k, v, out, lse = res
    return _pallas_backward(q, k, v, out, lse, g, is_causal, scale,
                            block_q, block_k)


_flash_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


def flash_attention_fwd(q, k, v, mask=None, is_causal=False, scale=None,
                        block_q=None, block_k=None):
    """q,k,v: [B,H,S,D].  Uses the Pallas kernels when mask is None and shapes
    tile; otherwise the XLA composed reference.  Fully differentiable with a
    Pallas backward (dq/dk/dv kernels recomputing P from the saved
    logsumexp).  Block sizes: explicit arguments win; otherwise the
    per-shape measured winners from flash_autotune_cache.json (written
    by tools/bench_kernels.py — deep-K blocks like 512x1024 win past
    S=1024), falling back to 512x512 shrunk by `pick_blocks` for
    sequences they don't divide.

    Causal cross-length attention (seq_q != seq_k) always takes the XLA
    reference: its causal mask is bottom-right aligned (tril offset
    kl-ql), while the kernels mask top-left (q_pos >= k_pos) — the two
    only agree at seq_q == seq_k."""
    # explicit caller blocks win; the measured cache only fills the
    # default case, then the divisibility heuristic
    if block_q is not None or block_k is not None:
        picked = pick_blocks(q.shape[-2], k.shape[-2],
                             block_q or 512, block_k or 512)
    else:
        picked = cached_blocks(q.shape[-2], k.shape[-2], q.shape[-1],
                               q.dtype, is_causal) or \
            pick_blocks(q.shape[-2], k.shape[-2])
    if (not _HAS_PALLAS or mask is not None or picked is None
            or (is_causal and q.shape[-2] != k.shape[-2])
            or jax.default_backend() != "tpu"):
        return _xla_reference(q, k, v, mask, is_causal, scale)
    block_q, block_k = picked
    # Policy: flag FLAGS_use_pallas_attention: "auto" (default; threshold
    # from the measured crossover vs XLA's fused attention, see
    # BENCH_kernels.json), "1"/"0" force on/off.
    if not pallas_attention_wanted(q.shape[-2], is_causal):
        return _xla_reference(q, k, v, mask, is_causal, scale)
    return _flash_diff(q, k, v, is_causal, scale, block_q, block_k)


def _auto_threshold(is_causal: bool):
    from ...core import flags as _flags

    try:
        base = int(_flags.flag("pallas_attention_min_seq"))
    except Exception:
        base = 512
    # the S=512 crossover was measured causal-only (the dead-block DMA
    # clamps do nothing for full attention); non-causal keeps the round-2
    # crossover of 1024
    return base if is_causal else max(base, 1024)


def pick_blocks(seq_q: int, seq_k: int, block_q: int = 512,
                block_k: int = 512):
    """Largest power-of-two blocks (floor 128) that tile the sequences;
    None when no tiling exists — the one block-selection policy shared by
    the single-device entry point and the ring blocks."""
    while block_q > 128 and seq_q % block_q:
        block_q //= 2
    while block_k > 128 and seq_k % block_k:
        block_k //= 2
    if seq_q % block_q or seq_k % block_k:
        return None
    return block_q, block_k


# -- measured block-size cache (round-5 VERDICT #6) -------------------------
# tools/bench_kernels.py sweeps (block_q, block_k) per
# (seq_q, seq_k, d, dtype, causal) on the live chip and commits the
# winners here; the entry point prefers a cached winner over the
# divisibility default when the caller left the blocks at their
# defaults.  Re-run the bench after kernel changes.
_AUTOTUNE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "flash_autotune_cache.json")
_AUTOTUNE: dict = {}
_AUTOTUNE_LOADED = False


def _autotune_key(seq_q, seq_k, d, dtype, causal):
    return f"{seq_q}x{seq_k}x{d}:{jnp.dtype(dtype).name}:" \
           f"{'causal' if causal else 'full'}"


def _load_autotune():
    global _AUTOTUNE_LOADED
    if _AUTOTUNE_LOADED:
        return _AUTOTUNE
    _AUTOTUNE_LOADED = True
    try:
        import json

        with open(_AUTOTUNE_FILE) as f:
            _AUTOTUNE.update(json.load(f).get("entries", {}))
    except (OSError, ValueError, AttributeError, TypeError):
        # a missing/truncated/corrupt cache must degrade to the
        # divisibility default, never crash the attention hot path
        pass
    return _AUTOTUNE


def cached_blocks(seq_q, seq_k, d, dtype, causal):
    """Measured (block_q, block_k) for this shape, or None.  A stale
    or malformed entry (wrong arity, sub-tile block, no longer tiling
    the sequences) is ignored — cached values must survive the same
    minimum-tile/shrink rules `pick_blocks` enforces before they reach
    the Pallas kernel, degrading to the default rather than failing the
    hot path on a hand-edited or stale cache file (ADVICE round 5)."""
    ent = _load_autotune().get(
        _autotune_key(seq_q, seq_k, d, dtype, causal))
    try:
        bq, bk = int(ent[0]), int(ent[1])
    except (TypeError, ValueError, IndexError, KeyError):
        return None
    if bq < 128 or bk < 128:
        # below the kernel's minimum tile (pick_blocks' shrink floor)
        return None
    if pick_blocks(seq_q, seq_k, bq, bk) != (bq, bk):
        # pick_blocks would have shrunk or rejected these blocks — the
        # entry no longer tiles this shape; fall back to the default
        return None
    return bq, bk


def pallas_attention_wanted(seq_len: int, is_causal: bool = True) -> bool:
    """Shared FLAGS_use_pallas_attention policy ('1'/'0' force, 'auto'
    applies the measured seq threshold) — the single gate used by both the
    single-device kernel and the ring-attention blocks."""
    from ...core import flags as _flags

    if not _HAS_PALLAS or jax.default_backend() != "tpu":
        return False
    try:
        pol = str(_flags.flag("use_pallas_attention"))
    except Exception:
        return False
    if pol in ("1", "True", "true"):
        return True
    return pol == "auto" and seq_len >= _auto_threshold(is_causal)
