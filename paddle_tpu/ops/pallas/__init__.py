"""Pallas TPU kernels for the hot ops (reference `operators/fused/` CUDA
kernels → Pallas; SURVEY.md §7 build stage 8)."""
