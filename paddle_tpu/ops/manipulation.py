"""Shape/layout manipulation ops.

Reference: reshape2/transpose2/concat/split/stack/slice/strided_slice/
gather/gather_nd/scatter/tile/expand_v2/flip/roll/squeeze2/unsqueeze2/...
(`paddle/fluid/operators/*.cc`); Python API
`python/paddle/tensor/manipulation.py`.  All are pure layout ops for XLA.
"""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ..core.dispatch import dispatch
from ..core.tensor import Tensor, unwrap


def _ints(v):
    if isinstance(v, Tensor):
        v = v.tolist()
    if isinstance(v, (list, tuple)):
        return [int(unwrap(i)) if isinstance(i, Tensor) else int(i) for i in v]
    return int(v)


def cast(x, dtype):
    dt = dtype_mod.convert_dtype(dtype)
    return dispatch(lambda a: a.astype(dt), x)


def reshape(x, shape, name=None):
    shape = _ints(shape)
    return dispatch(lambda a: jnp.reshape(a, shape), x)


def transpose(x, perm, name=None):
    perm = _ints(perm)
    return dispatch(lambda a: jnp.transpose(a, perm), x)


def t(x, name=None):
    return dispatch(lambda a: a.T, x)


def concat(x, axis=0, name=None):
    axis = int(unwrap(axis))
    xs = list(x)
    return dispatch(lambda *a: jnp.concatenate(a, axis=axis), *xs)


def stack(x, axis=0, name=None):
    xs = list(x)
    return dispatch(lambda *a: jnp.stack(a, axis=axis), *xs)


def split(x, num_or_sections, axis=0, name=None):
    axis = int(unwrap(axis))
    n = x.shape[axis]
    if isinstance(num_or_sections, int):
        sections = num_or_sections
        outs = dispatch(
            lambda a: tuple(jnp.split(a, sections, axis=axis)), x
        )
    else:
        secs = _ints(num_or_sections)
        # allow one -1 to infer
        if -1 in secs:
            known = builtins.sum(s for s in secs if s != -1)
            secs = [n - known if s == -1 else s for s in secs]
        idx = []
        acc = 0
        for s in secs[:-1]:
            acc += s
            idx.append(acc)
        outs = dispatch(lambda a: tuple(jnp.split(a, idx, axis=axis)), x)
    return list(outs)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def squeeze(x, axis=None, name=None):
    if axis is None:
        return dispatch(lambda a: jnp.squeeze(a), x)
    axes = _ints(axis if isinstance(axis, (list, tuple)) else [axis])
    def f(a):
        sq = tuple(ax for ax in axes if a.shape[ax] == 1)
        return jnp.squeeze(a, axis=sq) if sq else a
    return dispatch(f, x)


def unsqueeze(x, axis, name=None):
    axes = _ints(axis if isinstance(axis, (list, tuple)) else [axis])
    def f(a):
        for ax in sorted(axes):
            a = jnp.expand_dims(a, ax)
        return a
    return dispatch(f, x)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def f(a):
        nd = a.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = a.shape[:s] + (-1,) + a.shape[e + 1 :]
        return jnp.reshape(a, new_shape)

    return dispatch(f, x)


def tile(x, repeat_times, name=None):
    reps = _ints(repeat_times)
    return dispatch(lambda a: jnp.tile(a, reps), x)


def expand(x, shape, name=None):
    shape = _ints(shape)
    def f(a):
        tgt = list(shape)
        for i, s in enumerate(tgt):
            if s == -1:
                tgt[i] = a.shape[i - len(tgt) + a.ndim]
        return jnp.broadcast_to(a, tgt)
    return dispatch(f, x)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def expand_as(x, y, name=None):
    return dispatch(lambda a, b: jnp.broadcast_to(a, b.shape), x, y)


def broadcast_tensors(inputs, name=None):
    arrs = jnp.broadcast_arrays(*[unwrap(i) for i in inputs])
    return [Tensor(a) for a in arrs]


def flip(x, axis, name=None):
    axes = _ints(axis if isinstance(axis, (list, tuple)) else [axis])
    return dispatch(lambda a: jnp.flip(a, axis=tuple(axes)), x)


def roll(x, shifts, axis=None, name=None):
    return dispatch(lambda a: jnp.roll(a, shifts, axis=axis), x)


def rot90(x, k=1, axes=(0, 1), name=None):
    return dispatch(lambda a: jnp.rot90(a, k=k, axes=axes), x)


def gather(x, index, axis=0, name=None):
    axis = int(unwrap(axis)) if axis is not None else 0
    return dispatch(
        lambda a, i: jnp.take(a, i.reshape(-1) if i.ndim > 1 else i, axis=axis),
        x,
        index,
        nondiff=(1,),
    )


def gather_nd(x, index, name=None):
    def f(a, i):
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return a[idx]

    return dispatch(f, x, index, nondiff=(1,))


def take_along_axis(arr, indices, axis, name=None):
    return dispatch(
        lambda a, i: jnp.take_along_axis(a, i, axis=axis), arr, indices, nondiff=(1,)
    )


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    def f(a, i, v):
        v = jnp.broadcast_to(v, i.shape).astype(a.dtype)
        dims = [jnp.arange(s).reshape([-1 if d == k else 1 for k in range(i.ndim)])
                for d, s in enumerate(i.shape)]
        idx = tuple(i if d == axis else jnp.broadcast_to(dims[d], i.shape) for d in range(i.ndim))
        if reduce == "assign":
            return a.at[idx].set(v)
        if reduce == "add":
            return a.at[idx].add(v)
        if reduce == "multiply":
            return a.at[idx].multiply(v)
        raise ValueError(reduce)

    return dispatch(f, arr, indices, values, nondiff=(1,))


def scatter(x, index, updates, overwrite=True, name=None):
    def f(a, i, u):
        i = i.reshape(-1)
        if overwrite:
            return a.at[i].set(u)
        z = a.at[i].set(jnp.zeros_like(u))
        return z.at[i].add(u)

    return dispatch(f, x, index, updates, nondiff=(1,))


def scatter_nd_add(x, index, updates, name=None):
    def f(a, i, u):
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return a.at[idx].add(u)

    return dispatch(f, x, index, updates, nondiff=(1,))


def scatter_nd(index, updates, shape, name=None):
    def f(i, u):
        out = jnp.zeros(_ints(shape), u.dtype)
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return out.at[idx].add(u)

    return dispatch(f, index, updates, nondiff=(0,))


def index_select(x, index, axis=0, name=None):
    return gather(x, index, axis)


def index_sample(x, index, name=None):
    def f(a, i):
        rows = jnp.arange(a.shape[0])[:, None]
        return a[rows, i]

    return dispatch(f, x, index, nondiff=(1,))


def index_add(x, index, axis, value, name=None):
    def f(a, i, v):
        idx = [builtins.slice(None)] * a.ndim
        idx[axis] = i
        return a.at[tuple(idx)].add(v)

    return dispatch(f, x, index, value, nondiff=(1,))


def masked_select(x, mask, name=None):
    # dynamic-shaped output: host fallback (reference keeps this on CPU too
    # for small control work; not a jit-path op)
    import numpy as np

    a, m = np.asarray(unwrap(x)), np.asarray(unwrap(mask))
    return Tensor(a[m.astype(bool)])


def masked_fill(x, mask, value, name=None):
    v = unwrap(value)
    return dispatch(lambda a, m: jnp.where(m.astype(bool), v, a), x, mask, nondiff=(1,))


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=False)
    return dispatch(
        lambda c, a, b: jnp.where(c.astype(bool), a, b), condition, x, y, nondiff=(0,)
    )


def nonzero(x, as_tuple=False):
    import numpy as np

    a = np.asarray(unwrap(x))
    nz = np.nonzero(a)
    if as_tuple:
        return tuple(Tensor(np.asarray(i)) for i in nz)
    return Tensor(np.stack(nz, axis=-1).astype(np.int64))


def slice(input, axes, starts, ends, name=None):
    axes, starts, ends = _ints(axes), _ints(starts), _ints(ends)

    def f(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e in zip(axes, starts, ends):
            idx[ax] = builtins.slice(s, e)
        return a[tuple(idx)]

    return dispatch(f, input)


def strided_slice(x, axes, starts, ends, strides, name=None):
    axes, starts, ends, strides = _ints(axes), _ints(starts), _ints(ends), _ints(strides)

    def f(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = builtins.slice(s, e, st)
        return a[tuple(idx)]

    return dispatch(f, x)


def unbind(input, axis=0, name=None):
    n = input.shape[axis]
    outs = dispatch(
        lambda a: tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(a, n, axis=axis)),
        input,
    )
    return list(outs)


def unstack(x, axis=0, num=None, name=None):
    return unbind(x, axis)


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, name=None):
    import numpy as np

    a = np.asarray(unwrap(x))
    res = np.unique(a, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(res)
    return tuple(Tensor(r) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, name=None):
    import numpy as np

    a = np.asarray(unwrap(x))
    if axis is None:
        a = a.reshape(-1)
    keep = np.concatenate([[True], a[1:] != a[:-1]]) if a.ndim == 1 else None
    out = a[keep]
    res = [Tensor(out)]
    if return_inverse:
        res.append(Tensor(np.cumsum(keep) - 1))
    if return_counts:
        idx = np.flatnonzero(keep)
        res.append(Tensor(np.diff(np.append(idx, a.shape[0]))))
    return res[0] if len(res) == 1 else tuple(res)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    pad = _ints(pad)

    def f(a):
        nd = a.ndim
        if len(pad) == 2 * nd:
            width = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # paddle convention: pad applies to last len(pad)//2 dims,
            # in reverse order pairs, honoring data_format for 4D/5D
            width = [(0, 0)] * nd
            npairs = len(pad) // 2
            if data_format.startswith("NC") and nd >= 3:
                dims = list(range(2, nd))
            else:
                dims = list(range(1, nd - 1))
            dims = dims[-npairs:] if npairs <= len(dims) else list(range(nd - npairs, nd))
            for k, d in enumerate(dims):
                width[d] = (pad[2 * k], pad[2 * k + 1])
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
                 "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, width, mode="constant", constant_values=value)
        return jnp.pad(a, width, mode=jmode)

    return dispatch(f, x)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    size = index_num // nshards

    def f(i):
        in_shard = (i // size) == shard_id
        return jnp.where(in_shard, i % size, ignore_value)

    return dispatch(f, input)


def repeat_interleave(x, repeats, axis=None, name=None):
    return dispatch(lambda a: jnp.repeat(a, repeats, axis=axis), x)


def moveaxis(x, source, destination, name=None):
    return dispatch(lambda a: jnp.moveaxis(a, source, destination), x)


def swapaxes(x, axis1, axis2, name=None):
    return dispatch(lambda a: jnp.swapaxes(a, axis1, axis2), x)


def as_complex(x, name=None):
    return dispatch(lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x)


def as_real(x, name=None):
    return dispatch(lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), x)


def tensordot(x, y, axes=2, name=None):
    return dispatch(lambda a, b: jnp.tensordot(a, b, axes=axes), x, y)


def numel(x, name=None):
    return Tensor(jnp.asarray(x.size))


def shape(input):
    return Tensor(jnp.asarray(input.shape, dtype=jnp.int32))


def crop(x, shape=None, offsets=None, name=None):
    shp = _ints(shape)
    offs = _ints(offsets) if offsets is not None else [0] * len(shp)

    def f(a):
        idx = tuple(builtins.slice(o, o + s) for o, s in zip(offs, shp))
        return a[idx]

    return dispatch(f, x)
