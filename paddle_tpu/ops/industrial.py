"""Industrial / niche long-tail operators (CTR, tree models, text match).

The final DESCOPED batch from the op inventory, implemented with the
repo's static-shape redesigns (LoD -> padded + lengths; dynamic row
counts -> front-compaction + validity masks).  Each op cites its
reference kernel and documents any divergence.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import dispatch

__all__ = [
    "tdm_child", "tdm_sampler", "rank_attention", "match_matrix_tensor",
    "var_conv_2d", "filter_by_instag", "tree_conv", "pyramid_hash",
    "lstmp", "sample_logits",
]


def tdm_child(x, tree_info, child_nums, dtype="int64", name=None):
    """Tree-based-deep-match child lookup (`operators/tdm_child_op.h`
    TDMChildInner).  ``tree_info`` rows are
    ``[item_id, layer_id, ancestor_id, child_0 .. child_{n-1}]``; node 0
    and childless nodes yield zeros.  Returns (child, leaf_mask) shaped
    ``x.shape[:-1] + (child_nums,)`` for trailing-1 inputs (the
    reference's [N, 1] convention), else ``x.shape + (child_nums,)``."""
    child_nums = int(child_nums)

    def f(ids, info):
        flat = ids.reshape(-1).astype(jnp.int32)
        children = info[flat, 3:3 + child_nums].astype(jnp.int64)
        has_child = (flat != 0) & (info[flat, 3] != 0)
        children = jnp.where(has_child[:, None], children, 0)
        is_item = (info[children.reshape(-1).astype(jnp.int32), 0] != 0)
        mask = is_item.reshape(children.shape) & has_child[:, None]
        shape = (ids.shape[:-1] if ids.shape and ids.shape[-1] == 1
                 else ids.shape) + (child_nums,)
        return (children.reshape(shape),
                mask.astype(jnp.int64).reshape(shape))

    child, mask = dispatch(f, x, tree_info)
    return child, mask


def tdm_sampler(x, travel, layer, neg_samples_num_list, layer_offset_lod,
                output_positive=True, seed=0, name=None):
    """Tree-based-deep-match layerwise sampler
    (`operators/tdm_sampler_op.h` TDMSamplerInner): for each input item,
    the positive node per tree layer comes from its travel path, plus
    ``neg_samples_num_list[l]`` negatives drawn uniformly WITHOUT
    replacement from layer ``l`` excluding the positive.  Padding
    positions (travel id 0) emit zeros with mask 0.

    TPU redesign: sampling-without-replacement is Gumbel-top-k over the
    layer's nodes (iid scores, positive masked to -inf) instead of the
    reference's rejection loop — same distribution, fixed shapes.
    Returns (out, labels, mask) each [N, sum(neg_l + output_positive)]."""
    negs = [int(n) for n in neg_samples_num_list]
    offs = [int(o) for o in layer_offset_lod]
    pos_flag = 1 if output_positive else 0

    def f(ids, trav, lay):
        from ..core import framework

        key = framework.make_rng_key(int(seed))
        n = ids.reshape(-1).shape[0]
        trav_rows = trav[ids.reshape(-1).astype(jnp.int32)]  # [N, L]
        outs, labels, masks = [], [], []
        for li, neg in enumerate(negs):
            lo, hi = offs[li], offs[li + 1]
            nodes = lay.reshape(-1)[lo:hi]                  # [nl]
            pos = trav_rows[:, li]                          # [N]
            alive = pos != 0
            if pos_flag:
                outs.append(jnp.where(alive, pos, 0)[:, None])
                labels.append(jnp.where(alive, 1, 0)[:, None])
                masks.append(jnp.where(alive, 1, 0)[:, None])
            scores = jax.random.uniform(
                jax.random.fold_in(key, li), (n, hi - lo))
            scores = jnp.where(nodes[None, :] == pos[:, None],
                               -jnp.inf, scores)
            _, idx = jax.lax.top_k(scores, neg)             # [N, neg]
            sampled = nodes[idx]
            outs.append(jnp.where(alive[:, None], sampled, 0))
            labels.append(jnp.zeros((n, neg), jnp.int64))
            masks.append(jnp.where(alive[:, None],
                                   jnp.ones((n, neg), jnp.int64), 0))
        return (jnp.concatenate(outs, -1).astype(jnp.int64),
                jnp.concatenate(labels, -1).astype(jnp.int64),
                jnp.concatenate(masks, -1).astype(jnp.int64))

    return dispatch(f, x, travel, layer)


def rank_attention(x, rank_offset, rank_param, max_rank=3, max_size=0,
                   name=None):
    """CTR rank attention (`operators/rank_attention_op.cu`
    expand_input_by_rank / expand_rank_attention_param): for instance i
    with rank r = rank_offset[i,0]-1 and per-slot pairs
    (faster_k, index_k) = rank_offset[i, 2k+1]-1, rank_offset[i, 2k+2],
    the parameter block ``rank_param[(r*max_rank + faster_k)*d : ...]``
    multiplies the features of instance ``index_k``.  Returns
    (out [N, p], input_help [N, max_rank*d], ins_rank [N, 1])."""
    k = int(max_rank)

    def f(xv, ro, par):
        n, d = xv.shape
        p = par.shape[1]
        lower = ro[:, 0].astype(jnp.int32) - 1                   # [N]
        faster = ro[:, 1::2][:, :k].astype(jnp.int32) - 1        # [N, K]
        index = ro[:, 2::2][:, :k].astype(jnp.int32)             # [N, K]
        valid = (lower[:, None] >= 0) & (faster >= 0)
        ih = jnp.where(valid[..., None], xv[index], 0.0)         # [N,K,d]
        block = lower[:, None] * k + faster                      # [N, K]
        par3 = par.reshape(-1, d, p)                             # [K*K,d,p]
        sel = par3[jnp.clip(block, 0, par3.shape[0] - 1)]        # [N,K,d,p]
        sel = jnp.where(valid[..., None, None], sel, 0.0)
        out = jnp.einsum("nkd,nkdp->np", ih, sel)
        return (out, ih.reshape(n, k * d),
                ro[:, 0].astype(xv.dtype)[:, None])

    return dispatch(f, x, rank_offset, rank_param)


def match_matrix_tensor(x, y, w, x_lens=None, y_lens=None, dim_t=1,
                        name=None):
    """Text-match similarity tensor
    (`operators/match_matrix_tensor_op.cc`): out[b,t,i,j] =
    x_i^T W_t y_j per paired sequences.  Padded form: x [B, Lx, d],
    y [B, Ly, d] (+ lengths), W [d, dim_t, d]; returns
    (out [B, dim_t, Lx, Ly] zero outside valid positions,
    tmp [B, Lx, dim_t, d] = x W)."""
    def f(xv, yv, wv, *lens):
        tmp = jnp.einsum("bid,dte->bite", xv, wv)
        out = jnp.einsum("bite,bje->btij", tmp, yv)
        if lens:
            xl, yl = lens
            mi = jnp.arange(xv.shape[1])[None, :] < xl[:, None]
            mj = jnp.arange(yv.shape[1])[None, :] < yl[:, None]
            out = out * (mi[:, None, :, None] & mj[:, None, None, :])
            tmp = tmp * mi[..., None, None]
        return out, tmp

    if x_lens is not None:
        return dispatch(f, x, y, w, x_lens, y_lens)
    return dispatch(f, x, y, w)


def var_conv_2d(x, w, row_lens, col_lens, input_channel, output_channel,
                kernel_h, kernel_w, stride_h=1, stride_w=1, name=None):
    """Variable-size 2-D conv (`operators/var_conv_2d_op.cc`): each
    sample has its own HxW from the ROW/COLUMN LoDs; out sizes are
    ceil(h/stride) x ceil(w/stride) (implicit zero border padding).
    Padded form: x [B, C, Hmax, Wmax] + per-sample row/col lengths; the
    padded region is zero on input and the output is re-masked to each
    sample's own output size."""
    from ..nn import functional as F

    def f(xv, wv, rl, cl):
        b, c, hm, wm = xv.shape
        # zero beyond each sample's valid region (reference samples end)
        rmask = jnp.arange(hm)[None, :] < rl[:, None]
        cmask = jnp.arange(wm)[None, :] < cl[:, None]
        xv = xv * (rmask[:, None, :, None] & cmask[:, None, None, :])
        ker = wv.reshape(output_channel, input_channel, kernel_h, kernel_w)
        pad_h = ((kernel_h - 1) // 2, kernel_h // 2)
        pad_w = ((kernel_w - 1) // 2, kernel_w // 2)
        out = jax.lax.conv_general_dilated(
            xv, ker, (stride_h, stride_w), [pad_h, pad_w],
            dimension_numbers=jax.lax.conv_dimension_numbers(
                xv.shape, ker.shape, ("NCHW", "OIHW", "NCHW")))
        oh = (rl - 1) // stride_h + 1
        ow = (cl - 1) // stride_w + 1
        omr = jnp.arange(out.shape[2])[None, :] < oh[:, None]
        omc = jnp.arange(out.shape[3])[None, :] < ow[:, None]
        return out * (omr[:, None, :, None] & omc[:, None, None, :])

    return dispatch(f, x, w, row_lens, col_lens)


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True,
                     out_val_if_empty=0, name=None):
    """Instance-tag filter (`operators/filter_by_instag_op.cc`): keep
    instances whose tag set intersects ``filter_tag``.  Static-shape
    redesign: kept rows are compacted to the FRONT (stable order);
    padding rows hold ``out_val_if_empty``.  ``ins_tag`` is padded
    [N, K] with -1.  Returns (out [N, d], loss_weight [N, 1] — 1.0 on
    kept positions, index_map [N] original row (or -1 on padding))."""
    def f(iv, tags, ftag):
        n = iv.shape[0]
        hit = (tags[:, :, None] == ftag[None, None, :]) & \
            (tags[:, :, None] >= 0)
        keep = hit.any(axis=(1, 2))                       # [N]
        order = jnp.argsort(~keep, stable=True)           # kept first
        kept_sorted = keep[order]
        out = jnp.where(kept_sorted[:, None], iv[order],
                        jnp.asarray(out_val_if_empty, iv.dtype))
        lw = kept_sorted.astype(jnp.float32)[:, None]
        idx_map = jnp.where(kept_sorted, order, -1).astype(jnp.int64)
        return out, lw, idx_map

    return dispatch(f, ins, ins_tag, filter_tag)


def tree_conv(nodes_vector, edge_set, filter, max_depth, name=None):
    """Tree-based convolution (TBCNN, `operators/tree_conv_op.cc` +
    `math/tree2col.cc`): each node's patch is its subtree within
    ``max_depth``, weighted per direction by the eta_t/eta_l/eta_r
    coefficients; the patch contracts with ``filter``
    [F, 3, output_size, num_filters].

    TPU redesign: the reference's DFS patch construction becomes dense
    reachability (k-step powers of the child adjacency) — fine for the
    op's tree sizes.  nodes_vector [B, N, F]; edge_set [B, E, 2]
    (1-based parent->child ids, (0,0) padding).  Out
    [B, N, output_size, num_filters]; rows beyond a sample's node count
    are zero."""
    md = int(max_depth)

    def one(feat, edges, filt):
        n = feat.shape[0]
        u = edges[:, 0].astype(jnp.int32)
        v = edges[:, 1].astype(jnp.int32)
        evalid = (u != 0) & (v != 0)
        ui = jnp.where(evalid, u - 1, 0)
        vi = jnp.where(evalid, v - 1, 0)
        adj = jnp.zeros((n, n), jnp.float32).at[ui, vi].add(
            evalid.astype(jnp.float32))
        adj = jnp.minimum(adj, 1.0)
        # child position among siblings (edge order) and sibling count
        same_parent = (u[:, None] == u[None, :]) & evalid[None, :] & \
            evalid[:, None]
        earlier = same_parent & (jnp.arange(len(u))[None, :] <
                                 jnp.arange(len(u))[:, None])
        child_pos_e = earlier.sum(-1)                     # per edge
        sib_cnt_e = same_parent.sum(-1)
        # padding edges must not scatter into node 0: route their writes
        # to an out-of-range index and drop them (jnp scatter mode)
        vi_or_oob = jnp.where(evalid, vi, n)
        child_pos = jnp.zeros((n,), jnp.float32).at[vi_or_oob].add(
            child_pos_e.astype(jnp.float32), mode="drop")
        sib_cnt = jnp.ones((n,), jnp.float32).at[vi_or_oob].set(
            sib_cnt_e.astype(jnp.float32), mode="drop")
        # eta coefficients of node v at relative depth k under an ancestor
        def etas(depth, pos, cnt):
            eta_t = (md - depth) / md
            tmp = jnp.where(cnt == 1, 0.5, pos / jnp.maximum(cnt - 1, 1))
            eta_l = (1.0 - eta_t) * tmp
            eta_r = (1.0 - eta_t) * (1.0 - eta_l)
            return eta_t, eta_l, eta_r
        # accumulate per-direction patches: depth 0 = the node itself
        # (index=1, pclen=1 -> eta_l uses tmp=0.5)
        t0, l0, r0 = etas(jnp.zeros((n,)), jnp.zeros((n,)),
                          jnp.ones((n,)))
        acc_t = t0[:, None] * feat
        acc_l = l0[:, None] * feat
        acc_r = r0[:, None] * feat
        reach = jnp.eye(n, dtype=jnp.float32)
        tk, lk, rk = etas(jnp.arange(1, md, dtype=jnp.float32)[:, None],
                          child_pos[None, :], sib_cnt[None, :])
        for k in range(1, md):
            reach = jnp.minimum(reach @ adj, 1.0)         # depth-k desc
            acc_t = acc_t + reach @ (tk[k - 1][:, None] * feat)
            acc_l = acc_l + reach @ (lk[k - 1][:, None] * feat)
            acc_r = acc_r + reach @ (rk[k - 1][:, None] * feat)
        patch = jnp.stack([acc_l, acc_r, acc_t], axis=-1)  # [N, F, 3]
        return jnp.einsum("nfk,fkom->nom", patch, filt)

    def f(nv, es, filt):
        return jax.vmap(lambda a, b: one(a, b, filt))(nv, es)

    return dispatch(f, nodes_vector, edge_set, filter)


def _fnv_mix(h, salt):
    """FNV-1a-style integer mix (uint32 lattice) — the same stance as
    `ops.misc.hash_op`: the reference hashes with XXH32
    (`operators/pyramid_hash_op.cc` hash_embedding_ff); bucket
    distribution is equivalent for embedding lookups, bit-exact bucket
    ids are not preserved."""
    h = (h ^ jnp.uint32(salt)) * jnp.uint32(16777619)
    return h ^ (h >> 15)


def pyramid_hash(x, w, lengths=None, num_emb=8, space_len=1000,
                 pyramid_layer=2, rand_len=4, drop_out_percent=0.0,
                 is_training=False, seed=0, name=None):
    """Pyramid hash embedding (`operators/pyramid_hash_op.cc`): every
    n-gram of length 2..pyramid_layer hashes (per rand_len-chunk) into a
    flat weight table; the chunks concatenate to a num_emb-dim embedding
    per n-gram.

    Padded redesign: x [B, T] int32 token ids (+ per-sequence lengths);
    w is the flat table [space_len + rand_len].  Returns
    (out [B, T, L, num_emb], mask [B, T, L]) where layer l holds the
    n-gram x[b, t : t+l+2] and mask marks n-grams fully inside the
    sequence.  (The reference emits one LoD row per valid n-gram; this is
    the padded+mask equivalent.)  White/black bloom filters are not
    supported (documented divergence)."""
    assert num_emb % rand_len == 0
    layers = max(int(pyramid_layer) - 1, 1)

    def f(ids, wv, *lens):
        b, t = ids.shape
        idsu = ids.astype(jnp.uint32)
        length = (lens[0] if lens
                  else jnp.full((b,), t, jnp.int32))
        outs, masks = [], []
        for li in range(layers):
            ng = li + 2  # n-gram token count
            # rolling hash over the window
            h = jnp.zeros((b, t), jnp.uint32)
            for j in range(ng):
                tok = jnp.pad(idsu, ((0, 0), (0, ng)))[:, j:j + t]
                h = _fnv_mix(h ^ tok, 2654435761 + j)
            chunks = []
            for j in range(0, int(num_emb), int(rand_len)):
                pos = (_fnv_mix(h, j + 77) %
                       jnp.uint32(space_len)).astype(jnp.int32)
                idx = pos[..., None] + jnp.arange(rand_len)
                chunks.append(wv[idx])
            emb = jnp.concatenate(chunks, axis=-1)        # [B,T,num_emb]
            valid = (jnp.arange(t)[None, :] + ng) <= length[:, None]
            outs.append(jnp.where(valid[..., None], emb, 0.0))
            masks.append(valid)
        out = jnp.stack(outs, axis=2)                     # [B,T,L,E]
        mask = jnp.stack(masks, axis=2)
        if is_training and drop_out_percent > 0:
            from ..core import framework

            keep = jax.random.bernoulli(
                framework.make_rng_key(int(seed)),
                1.0 - float(drop_out_percent), mask.shape)
            out = out * keep[..., None]
            mask = mask & keep
        return out, mask.astype(jnp.int32)

    if lengths is not None:
        return dispatch(f, x, w, lengths)
    return dispatch(f, x, w)


def lstmp(x, weight, proj_weight, bias=None, h0=None, c0=None,
          use_peepholes=True, is_reverse=False, gate_activation="sigmoid",
          cell_activation="tanh", candidate_activation="tanh",
          proj_activation="tanh", name=None):
    """Projection LSTM (`operators/lstmp_op.cc`): the recurrent state is
    the PROJECTED hidden r_t = act_p(h_t @ proj_weight) [P], so the
    recurrent weight is [P, 4D].  Input x is the pre-projected sequence
    [B, T, 4D] (or [T, 4D]); gate order {c, i, f, o} like `lstm`; Bias
    [4D] (+3D peephole tail).  Returns (projection [.., T, P],
    cell [.., T, D])."""
    acts = {"sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
            "relu": lambda v: jnp.maximum(v, 0), "identity": lambda v: v}
    actg = acts[gate_activation]
    actc = acts[cell_activation]
    actn = acts[candidate_activation]
    actp = acts[proj_activation]

    def f(xv, wv, pw, *rest):
        d = pw.shape[0]
        p = pw.shape[1]
        single = xv.ndim == 2
        if single:
            xv = xv[None]
        b = xv.shape[0]
        rest = list(rest)
        bias_v = rest.pop(0).reshape(-1) if bias is not None else \
            jnp.zeros((4 * d,), xv.dtype)
        gb = bias_v[:4 * d]
        w_ic = w_fc = w_oc = None
        if use_peepholes and bias_v.size >= 7 * d:
            w_ic, w_fc, w_oc = (bias_v[4 * d:5 * d], bias_v[5 * d:6 * d],
                                bias_v[6 * d:7 * d])
        r_init = rest.pop(0).reshape(b, p) if h0 is not None else \
            jnp.zeros((b, p), xv.dtype)
        c_init = rest.pop(0).reshape(b, d) if c0 is not None else \
            jnp.zeros((b, d), xv.dtype)

        def step(carry, xt):
            r, c = carry
            g = xt + r @ wv + gb
            gc, gi, gf, go = jnp.split(g, 4, axis=-1)
            if w_ic is not None:
                gi = gi + c * w_ic
                gf = gf + c * w_fc
            i = actg(gi)
            fg = actg(gf)
            cand = actc(gc)
            c_new = fg * c + i * cand
            if w_oc is not None:
                go = go + c_new * w_oc
            o = actg(go)
            h_new = o * actn(c_new)
            r_new = actp(h_new @ pw)
            return (r_new, c_new), (r_new, c_new)

        _, (rs, cs) = jax.lax.scan(step, (r_init, c_init),
                                   jnp.moveaxis(xv, 1, 0),
                                   reverse=bool(is_reverse))
        proj = jnp.moveaxis(rs, 0, 1)
        cell = jnp.moveaxis(cs, 0, 1)
        if single:
            proj, cell = proj[0], cell[0]
        return proj, cell

    args = [x, weight, proj_weight]
    if bias is not None:
        args.append(bias)
    if h0 is not None:
        args.append(h0)
    if c0 is not None:
        args.append(c0)
    return dispatch(f, *args)


def sample_logits(logits, labels, num_samples, uniq=True,
                  remove_accidental_hits=True, use_customized_samples=False,
                  customized_samples=None, customized_probabilities=None,
                  seed=0, name=None):
    """Sampled-softmax helper (`operators/sample_logits_op.cc`): gather
    the true-label logits plus ``num_samples`` sampled negative logits
    and correct both by log(expected count) (log-uniform sampler), so a
    softmax_with_cross_entropy over the result estimates the full
    softmax.  Returns (sampled_logits [N, T+S], sampled_labels [N, T])
    where T = labels per row; accidental hits (a sampled id equal to a
    true label of that row) are masked to -1e20."""
    s = int(num_samples)

    def f(lg, lb, *cust):
        from ..core import framework

        n, v = lg.shape
        t = lb.shape[1]
        if cust:
            samples = cust[0].astype(jnp.int32)           # [S]
            probs = cust[1]
        else:
            # log-uniform (Zipf) candidate sampler, shared across rows
            # (the reference's LogUniformSampler).  uniq=True draws
            # WITHOUT replacement via Gumbel-top-k over the log-uniform
            # weights (the reference uses accept-reject; same resulting
            # distribution, static shapes); uniq=False draws iid.
            key = framework.make_rng_key(int(seed))
            logw = jnp.log(jnp.log((jnp.arange(v) + 2.0) /
                                   (jnp.arange(v) + 1.0)))
            if uniq:
                gumbel = -jnp.log(-jnp.log(
                    jax.random.uniform(key, (v,), minval=1e-20,
                                       maxval=1.0)))
                _, samples = jax.lax.top_k(logw + gumbel, s)
                samples = samples.astype(jnp.int32)
            else:
                u = jax.random.uniform(key, (s,))
                samples = (jnp.exp(u * jnp.log(v + 1.0)) - 1.0).astype(
                    jnp.int32)
                samples = jnp.clip(samples, 0, v - 1)
            probs = (jnp.log((samples + 2.0) / (samples + 1.0)) /
                     jnp.log(v + 1.0))
        true_logit = jnp.take_along_axis(lg, lb.astype(jnp.int32), 1)
        true_p = (jnp.log((lb + 2.0) / (lb + 1.0)) / jnp.log(v + 1.0))
        true_logit = true_logit - jnp.log(true_p * s + 1e-20)
        samp_logit = lg[:, samples] - jnp.log(probs * s + 1e-20)[None, :]
        if remove_accidental_hits:
            hit = (samples[None, None, :] == lb[:, :, None]).any(1)
            samp_logit = jnp.where(hit, -1e20, samp_logit)
        out = jnp.concatenate([true_logit, samp_logit], axis=1)
        new_labels = jnp.tile(jnp.arange(t), (n, 1))
        return out, new_labels.astype(jnp.int64)

    if use_customized_samples:
        return dispatch(f, logits, labels, customized_samples,
                        customized_probabilities)
    return dispatch(f, logits, labels)
