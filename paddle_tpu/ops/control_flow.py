"""Control-flow operators.

Reference: `python/paddle/fluid/layers/control_flow.py` (3.8 k LoC —
`cond`, `while_loop`, `case`, `switch_case` built on `conditional_block_op`
and `while_op` sub-block execution, `operators/controlflow/`).

TPU-native: these lower directly to `lax.cond` / `lax.while_loop` /
`lax.switch` — XLA's structured control flow, which compiles into the same
program instead of the reference's interpreter-driven sub-blocks.  Eager
mode short-circuits on concrete predicates (matching dygraph semantics
where Python `if` just works).
"""
from __future__ import annotations

from typing import Callable, List, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor, unwrap

__all__ = ["cond", "while_loop", "case", "switch_case"]


def _concrete(pred) -> bool | None:
    """Return a python bool if the predicate is concrete (eager), else
    None (tracing: must lower to lax)."""
    arr = unwrap(pred) if isinstance(pred, Tensor) else pred
    if isinstance(arr, (bool, int)):
        return bool(arr)
    if isinstance(arr, jax.core.Tracer):
        return None
    return bool(jax.device_get(arr))


def _unwrap_tree(x):
    return jax.tree_util.tree_map(
        lambda v: unwrap(v) if isinstance(v, Tensor) else v, x,
        is_leaf=lambda v: isinstance(v, Tensor))


def cond(pred, true_fn=None, false_fn=None, name=None):
    """reference `layers.cond` (`control_flow.py:2091` area): run one of two
    branches.  Under jit both branches trace (lax.cond); eagerly only the
    taken branch runs."""
    c = _concrete(pred)
    if c is not None:
        return true_fn() if c else false_fn()
    p = unwrap(pred).reshape(())

    def tf(_):
        return _unwrap_tree(true_fn())

    def ff(_):
        return _unwrap_tree(false_fn())

    out = lax.cond(p, tf, ff, operand=None)
    return jax.tree_util.tree_map(Tensor, out)


def while_loop(cond_fn: Callable, body_fn: Callable, loop_vars: Sequence,
               is_test=False, name=None):
    """reference `layers.while_loop` (`control_flow.py:1014`): iterate
    body while cond holds.  Shapes must be loop-invariant (the reference's
    while_op has the same constraint for compiled use)."""
    vars_ = list(loop_vars)
    c = _concrete(cond_fn(*vars_))
    if c is not None:
        # eager loop: concrete python iteration (dygraph semantics)
        while c:
            out = body_fn(*vars_)
            vars_ = list(out) if isinstance(out, (list, tuple)) else [out]
            c = _concrete(cond_fn(*vars_))
            if c is None:
                raise RuntimeError("predicate became abstract mid-loop")
        return vars_

    init = tuple(_unwrap_tree(v) for v in vars_)

    def cf(state):
        return unwrap(cond_fn(*[Tensor(s) if not isinstance(s, Tensor)
                                else s for s in state])).reshape(())

    def bf(state):
        out = body_fn(*[Tensor(s) for s in state])
        out = out if isinstance(out, (list, tuple)) else (out,)
        return tuple(_unwrap_tree(o) for o in out)

    final = lax.while_loop(cf, bf, init)
    return [Tensor(f) for f in final]


def case(pred_fn_pairs, default=None, name=None):
    """reference `layers.case` (`control_flow.py:2811`): first true
    predicate wins."""
    pairs = list(pred_fn_pairs)
    concretes = [_concrete(p) for p, _ in pairs]
    if all(c is not None for c in concretes):
        for c, (_, fn) in zip(concretes, pairs):
            if c:
                return fn()
        # reference semantics: with no default, the LAST pair's fn is the
        # fallback (fluid layers.case docstring) — matches the traced path
        return default() if default is not None else pairs[-1][1]()
    if default is None:
        default = pairs[-1][1]
        pairs = pairs[:-1]
    # jnp.asarray: a mix of concrete python bools and traced predicates is
    # legal (config constant + data-dependent) and must all lift to arrays
    preds = jnp.stack([jnp.asarray(unwrap(p)).reshape(()) for p, _ in pairs])
    # index of first true predicate; len(pairs) = default
    first = jnp.argmax(preds)
    idx = jnp.where(jnp.any(preds), first, len(pairs))
    fns = [fn for _, fn in pairs] + [default]

    out = lax.switch(idx, [lambda _, f=f: _unwrap_tree(f()) for f in fns],
                     None)
    return jax.tree_util.tree_map(Tensor, out)


def switch_case(branch_index, branch_fns, default=None, name=None):
    """reference `layers.switch_case` (`control_flow.py:2990`)."""
    if isinstance(branch_fns, dict):
        keys = sorted(branch_fns)
        fns = [branch_fns[k] for k in keys]
        index_map = {k: i for i, k in enumerate(keys)}
    else:
        fns = list(branch_fns)
        index_map = None
    bi = _concrete_index(branch_index)
    if bi is not None:
        i = index_map.get(bi) if index_map is not None else (
            bi if 0 <= bi < len(fns) else None)
        if i is None:
            if default is None:
                raise ValueError(f"branch {bi} out of range, no default")
            return default()
        return fns[i]()
    # traced index
    if default is None:
        default = fns[-1]
    arr = unwrap(branch_index).reshape(())
    if index_map is not None:
        keys_arr = jnp.asarray(sorted(index_map))
        matches = keys_arr == arr
        idx = jnp.where(jnp.any(matches), jnp.argmax(matches), len(fns))
    else:
        idx = jnp.where((arr >= 0) & (arr < len(fns)), arr, len(fns))
    all_fns = fns + [default]
    out = lax.switch(idx, [lambda _, f=f: _unwrap_tree(f())
                           for f in all_fns], None)
    return jax.tree_util.tree_map(Tensor, out)


def _concrete_index(i):
    arr = unwrap(i) if isinstance(i, Tensor) else i
    if isinstance(arr, int):
        return arr
    if isinstance(arr, jax.core.Tracer):
        return None
    return int(jax.device_get(arr))
