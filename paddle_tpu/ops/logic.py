"""Comparison / logical / bitwise ops.

Reference: `operators/controlflow/compare_op.cc` macro family, logical ops,
`python/paddle/tensor/logic.py`.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import dispatch
from ..core.tensor import Tensor, unwrap


def _cmp(jfn):
    def op(x, y, name=None):
        return Tensor(jfn(unwrap(x), unwrap(y)))

    return op


equal = _cmp(jnp.equal)
not_equal = _cmp(jnp.not_equal)
less_than = _cmp(jnp.less)
less_equal = _cmp(jnp.less_equal)
greater_than = _cmp(jnp.greater)
greater_equal = _cmp(jnp.greater_equal)


def equal_all(x, y, name=None):
    return Tensor(jnp.array_equal(unwrap(x), unwrap(y)))


def logical_and(x, y, out=None, name=None):
    return Tensor(jnp.logical_and(unwrap(x), unwrap(y)))


def logical_or(x, y, out=None, name=None):
    return Tensor(jnp.logical_or(unwrap(x), unwrap(y)))


def logical_xor(x, y, out=None, name=None):
    return Tensor(jnp.logical_xor(unwrap(x), unwrap(y)))


def logical_not(x, out=None, name=None):
    return Tensor(jnp.logical_not(unwrap(x)))


def bitwise_and(x, y, out=None, name=None):
    return Tensor(jnp.bitwise_and(unwrap(x), unwrap(y)))


def bitwise_or(x, y, out=None, name=None):
    return Tensor(jnp.bitwise_or(unwrap(x), unwrap(y)))


def bitwise_xor(x, y, out=None, name=None):
    return Tensor(jnp.bitwise_xor(unwrap(x), unwrap(y)))


def bitwise_not(x, out=None, name=None):
    return Tensor(jnp.bitwise_not(unwrap(x)))


def all(x, axis=None, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return Tensor(jnp.all(unwrap(x), axis=ax, keepdims=keepdim))


def any(x, axis=None, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return Tensor(jnp.any(unwrap(x), axis=ax, keepdims=keepdim))


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)
