"""Functional op library.

This package is the TPU-native replacement for the reference's operator
library (`paddle/fluid/operators/`, ~700 op types, SURVEY.md §2.1 "Dense op
library"): every op is a pure jnp/lax function executed through
`core.dispatch` (eager + autograd tape) and equally usable under a jit trace.
Tensor methods and Python operators are attached here, mirroring the
reference's `varbase_patch_methods.py` monkey-patching.
"""
from __future__ import annotations

from ..core.tensor import Tensor

from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .sequence import *  # noqa: F401,F403
from .misc import *  # noqa: F401,F403
from .crf import *  # noqa: F401,F403
from .industrial import *  # noqa: F401,F403
# control_flow exposed as a namespace only: its `cond` (branching) must not
# shadow linalg's `cond` (condition number) at the top level
from . import (control_flow, creation, crf, linalg, logic, manipulation,
               math, random, search, sequence, stat)
from .control_flow import case, switch_case, while_loop


def _attach_methods():
    import builtins

    from . import math as m
    from . import manipulation as mp
    from . import creation as cr
    from . import logic as lg
    from . import search as se
    from . import stat as st
    from . import linalg as la

    method_sources = {}
    for mod in (m, mp, cr, lg, se, st, la):
        for name in dir(mod):
            if name.startswith("_"):
                continue
            fn = getattr(mod, name)
            if callable(fn) and getattr(fn, "__module__", None) == mod.__name__:
                method_sources.setdefault(name, fn)

    skip = {"is_tensor", "meshgrid", "broadcast_tensors", "shape"}
    for name, fn in method_sources.items():
        if name in skip or hasattr(Tensor, name):
            continue
        setattr(Tensor, name, fn)

    # Python operator protocol
    Tensor.__add__ = lambda s, o: m.add(s, o)
    Tensor.__radd__ = lambda s, o: m.add(o if isinstance(o, Tensor) else Tensor(o), s)
    Tensor.__sub__ = lambda s, o: m.subtract(s, o)
    Tensor.__rsub__ = lambda s, o: m.subtract(o if isinstance(o, Tensor) else Tensor(o), s)
    Tensor.__mul__ = lambda s, o: m.multiply(s, o)
    Tensor.__rmul__ = lambda s, o: m.multiply(o if isinstance(o, Tensor) else Tensor(o), s)
    Tensor.__truediv__ = lambda s, o: m.divide(s, o)
    Tensor.__rtruediv__ = lambda s, o: m.divide(o if isinstance(o, Tensor) else Tensor(o), s)
    Tensor.__floordiv__ = lambda s, o: m.floor_divide(s, o)
    Tensor.__mod__ = lambda s, o: m.remainder(s, o)
    Tensor.__pow__ = lambda s, o: m.pow(s, o)
    Tensor.__rpow__ = lambda s, o: m.pow(o if isinstance(o, Tensor) else Tensor(o), s)
    Tensor.__neg__ = lambda s: m.neg(s)
    Tensor.__abs__ = lambda s: m.abs(s)
    Tensor.__matmul__ = lambda s, o: m.matmul(s, o)
    Tensor.__rmatmul__ = lambda s, o: m.matmul(o if isinstance(o, Tensor) else Tensor(o), s)
    Tensor.__eq__ = lambda s, o: lg.equal(s, o)
    Tensor.__ne__ = lambda s, o: lg.not_equal(s, o)
    Tensor.__lt__ = lambda s, o: lg.less_than(s, o)
    Tensor.__le__ = lambda s, o: lg.less_equal(s, o)
    Tensor.__gt__ = lambda s, o: lg.greater_than(s, o)
    Tensor.__ge__ = lambda s, o: lg.greater_equal(s, o)
    Tensor.__invert__ = lambda s: lg.logical_not(s)
    Tensor.__and__ = lambda s, o: lg.bitwise_and(s, o)
    Tensor.__or__ = lambda s, o: lg.bitwise_or(s, o)
    Tensor.__xor__ = lambda s, o: lg.bitwise_xor(s, o)
    # keep identity-based hashing despite __eq__ override
    Tensor.__hash__ = lambda s: id(s)

    # paddle-style dim helpers
    Tensor.dim = lambda s: s.ndim
    Tensor.rank = lambda s: s.ndim
    Tensor.numel = lambda s: s.size


_attach_methods()
del _attach_methods
