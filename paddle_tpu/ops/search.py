"""Search/sort ops: argmax/argmin/argsort/sort/topk/kthvalue/searchsorted/mode.

Reference: `operators/arg_max_op.cc`, `argsort_op.cc`, `top_k_v2_op.*`;
Python API `python/paddle/tensor/search.py`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ..core.dispatch import dispatch
from ..core.tensor import Tensor, unwrap


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    a = unwrap(x)
    out = jnp.argmax(a, axis=axis, keepdims=keepdim if axis is not None else False)
    return Tensor(out.astype(dtype_mod.convert_dtype(dtype)))


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    a = unwrap(x)
    out = jnp.argmin(a, axis=axis, keepdims=keepdim if axis is not None else False)
    return Tensor(out.astype(dtype_mod.convert_dtype(dtype)))


def argsort(x, axis=-1, descending=False, name=None):
    a = unwrap(x)
    idx = jnp.argsort(-a if descending else a, axis=axis)
    return Tensor(idx.astype(jnp.int64))


def sort(x, axis=-1, descending=False, name=None):
    def f(a):
        s = jnp.sort(a, axis=axis)
        return jnp.flip(s, axis=axis) if descending else s

    return dispatch(f, x)


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    k = int(unwrap(k))

    def f(a):
        ax = axis % a.ndim
        if ax != a.ndim - 1:
            a2 = jnp.moveaxis(a, ax, -1)
        else:
            a2 = a
        vals, idx = jax.lax.top_k(a2 if largest else -a2, k)
        if not largest:
            vals = -vals
        if ax != a.ndim - 1:
            vals = jnp.moveaxis(vals, -1, ax)
        return vals

    vals = dispatch(f, x)

    a = unwrap(x)
    ax = axis % a.ndim
    a2 = jnp.moveaxis(a, ax, -1) if ax != a.ndim - 1 else a
    _, idx = jax.lax.top_k(a2 if largest else -a2, k)
    if ax != a.ndim - 1:
        idx = jnp.moveaxis(idx, -1, ax)
    return vals, Tensor(idx.astype(jnp.int64))


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    a = unwrap(x)
    s = jnp.sort(a, axis=axis)
    i = jnp.argsort(a, axis=axis)
    vals = jnp.take(s, k - 1, axis=axis)
    idx = jnp.take(i, k - 1, axis=axis)
    if keepdim:
        vals = jnp.expand_dims(vals, axis)
        idx = jnp.expand_dims(idx, axis)
    return Tensor(vals), Tensor(idx.astype(jnp.int64))


def mode(x, axis=-1, keepdim=False, name=None):
    import scipy.stats as st
    import numpy as np

    a = np.asarray(unwrap(x))
    m = st.mode(a, axis=axis, keepdims=keepdim)
    return Tensor(m.mode.astype(a.dtype)), Tensor(np.asarray(m.count))


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    seq, v = unwrap(sorted_sequence), unwrap(values)
    side = "right" if right else "left"
    if seq.ndim == 1:
        out = jnp.searchsorted(seq, v, side=side)
    else:
        out = jax.vmap(lambda s, val: jnp.searchsorted(s, val, side=side))(
            seq.reshape(-1, seq.shape[-1]), v.reshape(-1, v.shape[-1])
        ).reshape(v.shape)
    return Tensor(out.astype(jnp.int32 if out_int32 else jnp.int64))


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)
