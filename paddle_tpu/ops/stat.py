"""Statistics ops: std/var/median/quantile/histogram.

Reference python API `python/paddle/tensor/stat.py`.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import dispatch
from ..core.tensor import Tensor, unwrap


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return dispatch(
        lambda a: jnp.var(a, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim),
        x,
    )


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return dispatch(
        lambda a: jnp.std(a, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim),
        x,
    )


def median(x, axis=None, keepdim=False, name=None):
    return dispatch(lambda a: jnp.median(a, axis=_axis(axis), keepdims=keepdim), x)


def nanmedian(x, axis=None, keepdim=False, name=None):
    return dispatch(lambda a: jnp.nanmedian(a, axis=_axis(axis), keepdims=keepdim), x)


def quantile(x, q, axis=None, keepdim=False, name=None):
    return dispatch(
        lambda a: jnp.quantile(a, jnp.asarray(q), axis=_axis(axis), keepdims=keepdim), x
    )


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return dispatch(
        lambda a: jnp.nanquantile(a, jnp.asarray(q), axis=_axis(axis), keepdims=keepdim), x
    )


def histogram(input, bins=100, min=0, max=0, name=None):
    a = unwrap(input)
    if min == 0 and max == 0:
        mn, mx = a.min(), a.max()
    else:
        mn, mx = min, max
    hist, _ = jnp.histogram(a, bins=bins, range=(float(mn), float(mx)))
    return Tensor(hist.astype(jnp.int64))


def bincount(x, weights=None, minlength=0, name=None):
    a = unwrap(x)
    w = unwrap(weights) if weights is not None else None
    length = int(jnp.maximum(a.max() + 1 if a.size else 0, minlength))
    return Tensor(jnp.bincount(a, weights=w, minlength=minlength, length=length))


def corrcoef(x, rowvar=True, name=None):
    return dispatch(lambda a: jnp.corrcoef(a, rowvar=rowvar), x)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return dispatch(
        lambda a: jnp.cov(
            a,
            rowvar=rowvar,
            ddof=1 if ddof else 0,
            fweights=unwrap(fweights) if fweights is not None else None,
            aweights=unwrap(aweights) if aweights is not None else None,
        ),
        x,
    )
