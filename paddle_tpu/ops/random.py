"""Random ops.

Reference: gaussian_random/uniform_random/randint/randperm/bernoulli/
multinomial/dropout ops (`operators/gaussian_random_op.cc` etc.), seeded by a
per-device generator.  TPU-native: JAX counter-based PRNG; eager mode draws
split keys from the global stateful generator (`paddle.seed`), trace mode
threads an explicit key (see core/framework.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ..core import framework
from ..core.tensor import Tensor, unwrap


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy().tolist())
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(unwrap(s)) if isinstance(s, Tensor) else int(s) for s in shape)


def _dt(dtype):
    return dtype_mod.convert_dtype(dtype) if dtype is not None else dtype_mod.get_default_dtype()


def seed(s):
    return framework.seed(s)


def get_rng_state():
    return framework.default_generator._key


def set_rng_state(key):
    framework.default_generator._key = key


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = framework.get_rng_key()
    return Tensor(
        jax.random.uniform(key, _shape(shape), _dt(dtype), minval=float(unwrap(min)), maxval=float(unwrap(max)))
    )


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, 0.0, 1.0)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    key = framework.get_rng_key()
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m, s = unwrap(mean), unwrap(std)
        shp = jnp.broadcast_shapes(
            getattr(m, "shape", ()), getattr(s, "shape", ())
        )
        return Tensor(jax.random.normal(key, shp, dtype_mod.get_default_dtype()) * s + m)
    return Tensor(
        jax.random.normal(key, _shape(shape), dtype_mod.get_default_dtype()) * std + mean
    )


def gaussian(shape, mean=0.0, std=1.0, dtype=None, name=None):
    key = framework.get_rng_key()
    return Tensor(jax.random.normal(key, _shape(shape), _dt(dtype)) * std + mean)


def randn(shape, dtype=None, name=None):
    return gaussian(shape, 0.0, 1.0, dtype)


def standard_normal(shape, dtype=None, name=None):
    return gaussian(shape, 0.0, 1.0, dtype)


def randint(low=0, high=None, shape=[1], dtype=None, name=None):
    if high is None:
        low, high = 0, low
    key = framework.get_rng_key()
    dt = dtype_mod.convert_dtype(dtype) if dtype else jnp.int64
    return Tensor(jax.random.randint(key, _shape(shape), int(low), int(high), dtype=dt))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    return randint(low, high, x.shape, dtype or x.dtype)


def randperm(n, dtype="int64", name=None):
    key = framework.get_rng_key()
    return Tensor(jax.random.permutation(key, n).astype(dtype_mod.convert_dtype(dtype)))


def shuffle(x, axis=0):
    key = framework.get_rng_key()
    return Tensor(jax.random.permutation(key, unwrap(x), axis=axis, independent=False))


def bernoulli(x, name=None):
    key = framework.get_rng_key()
    p = unwrap(x)
    return Tensor(jax.random.bernoulli(key, p).astype(p.dtype))


def poisson(x, name=None):
    key = framework.get_rng_key()
    lam = unwrap(x)
    return Tensor(jax.random.poisson(key, lam).astype(lam.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    key = framework.get_rng_key()
    p = unwrap(x)
    logits = jnp.log(jnp.maximum(p, 1e-30))
    if p.ndim == 1:
        out = jax.random.categorical(key, logits, shape=(num_samples,))
    else:
        out = jax.random.categorical(key, logits[:, None, :], axis=-1,
                                     shape=(p.shape[0], num_samples))
    return Tensor(out.astype(jnp.int64))


def normal_like(x, mean=0.0, std=1.0, name=None):
    key = framework.get_rng_key()
    a = unwrap(x)
    return Tensor(jax.random.normal(key, a.shape, a.dtype) * std + mean)
