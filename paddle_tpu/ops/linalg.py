"""Linear algebra ops.

Reference: `operators/cholesky_op.*`, `inverse_op.*`, `matrix_power`,
`p_norm_op.*`, `svd`, `eigh` etc.; Python API `python/paddle/tensor/linalg.py`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import dispatch
from ..core.tensor import Tensor, unwrap


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    def f(a):
        if p == "fro" and axis is None:
            return jnp.sqrt(jnp.sum(a * a))
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if p == "fro":
            return jnp.sqrt(jnp.sum(a * a, axis=ax, keepdims=keepdim))
        if p == float("inf"):
            return jnp.max(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == float("-inf"):
            return jnp.min(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=ax, keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)

    return dispatch(f, x)


def p_norm(x, p=2, axis=-1, keepdim=False):
    return norm(x, p, axis, keepdim)


def dist(x, y, p=2, name=None):
    return norm(dispatch(jnp.subtract, x, y), p)


def cholesky(x, upper=False, name=None):
    def f(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2) if upper else L

    return dispatch(f, x)


def inverse(x, name=None):
    return dispatch(jnp.linalg.inv, x)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return dispatch(lambda a: jnp.linalg.pinv(a, rcond=rcond, hermitian=hermitian), x)


def matrix_power(x, n, name=None):
    return dispatch(lambda a: jnp.linalg.matrix_power(a, n), x)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    a = unwrap(x)
    return Tensor(jnp.linalg.matrix_rank(a, tol=tol))


def det(x, name=None):
    return dispatch(jnp.linalg.det, x)


def slogdet(x, name=None):
    a = unwrap(x)
    sign, logdet = jnp.linalg.slogdet(a)
    return Tensor(jnp.stack([sign, logdet]))


def svd(x, full_matrices=False, name=None):
    a = unwrap(x)
    u, s, vh = jnp.linalg.svd(a, full_matrices=full_matrices)
    return Tensor(u), Tensor(s), Tensor(jnp.swapaxes(vh, -1, -2))


def qr(x, mode="reduced", name=None):
    a = unwrap(x)
    q, r = jnp.linalg.qr(a, mode=mode)
    return Tensor(q), Tensor(r)


def eigh(x, UPLO="L", name=None):
    a = unwrap(x)
    w, v = jnp.linalg.eigh(a, UPLO=UPLO)
    return Tensor(w), Tensor(v)


def eigvalsh(x, UPLO="L", name=None):
    return Tensor(jnp.linalg.eigvalsh(unwrap(x), UPLO=UPLO))


def eig(x, name=None):
    import numpy as np

    w, v = np.linalg.eig(np.asarray(unwrap(x)))
    return Tensor(w), Tensor(v)


def lstsq(x, y, rcond=None, driver=None, name=None):
    a, b = unwrap(x), unwrap(y)
    sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
    return Tensor(sol), Tensor(res), Tensor(rank), Tensor(sv)


def solve(x, y, name=None):
    return dispatch(jnp.linalg.solve, x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def f(a, b):
        if transpose:
            a = jnp.swapaxes(a, -1, -2)
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, unit_diagonal=unitriangular
        )

    return dispatch(f, x, y)


def cholesky_solve(x, y, upper=False, name=None):
    def f(b, L):
        return jax.scipy.linalg.cho_solve((L, not upper), b)

    return dispatch(f, x, y)


def cross(x, y, axis=9, name=None):
    def f(a, b):
        ax = axis
        if ax == 9:
            ax = next((i for i, s in enumerate(a.shape) if s == 3), -1)
        return jnp.cross(a, b, axis=ax)

    return dispatch(f, x, y)


def bilinear_tensor_product(x, y, weight, bias=None):
    def f(a, b, w, *bi):
        # w: [out, dx, dy]
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bi:
            out = out + bi[0]
        return out

    if bias is not None:
        return dispatch(f, x, y, weight, bias)
    return dispatch(f, x, y, weight)


def histogramdd(*args, **kwargs):
    raise NotImplementedError


def cond(x, p=None, name=None):
    """Condition number.  Reference: `python/paddle/tensor/linalg.py` cond."""
    def f(a):
        if p is None or p == 2 or p == "fro":
            s = jnp.linalg.svd(a, compute_uv=False)
            if p == "fro":
                return jnp.sqrt(jnp.sum(s * s, axis=-1)) * jnp.sqrt(
                    jnp.sum((1.0 / s) ** 2, axis=-1))
            return s[..., 0] / s[..., -1]
        if p == -2:
            s = jnp.linalg.svd(a, compute_uv=False)
            return s[..., -1] / s[..., 0]
        return jnp.linalg.norm(a, ord=p, axis=(-2, -1)) * jnp.linalg.norm(
            jnp.linalg.inv(a), ord=p, axis=(-2, -1))

    return dispatch(f, x)


def multi_dot(x, name=None):
    """Chained matmul; reference `python/paddle/tensor/linalg.py` multi_dot."""
    def f(*arrs):
        out = arrs[0]
        for a in arrs[1:]:
            out = jnp.matmul(out, a)
        return out

    return dispatch(f, *x)
