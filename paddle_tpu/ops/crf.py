"""Linear-chain CRF ops.

Reference: `operators/linear_chain_crf_op.h` (forward algorithm over a
[tag_num+2, tag_num] transition matrix whose row 0 holds start weights and
row 1 end weights) and `operators/crf_decoding_op.h` (Viterbi decode; with
a Label input the output becomes a per-position correctness indicator).

TPU-native: padded [B, T, N] emissions + lengths instead of LoD, the time
recursion as `lax.scan` (static shapes, masked past each sequence end), so
both ops trace into compiled steps.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import dispatch

__all__ = ["linear_chain_crf", "crf_decoding"]


def linear_chain_crf(emission, transition, label, length, name=None):
    """Negative log-likelihood cost per sequence (`linear_chain_crf` op).

    emission: [B, T, N] unnormalized tag scores; transition: [N+2, N]
    (row 0 start, row 1 end, rows 2+ the NxN transition matrix);
    label: [B, T] int tags; length: [B].  Returns [B, 1] cost
    = logZ - path_score (minimize to train, as in the reference book's
    label_semantic_roles example).
    """

    def f(em, trans, lab, ln):
        b, t, n = em.shape
        start, end, w = trans[0], trans[1], trans[2:]
        em = em.astype(jnp.float32)
        lab = lab.astype(jnp.int32)
        ln = ln.astype(jnp.int32)

        # ---- partition function via forward algorithm -------------------
        alpha0 = start[None, :] + em[:, 0, :]  # [B, N]

        def step(alpha, xs):
            em_t, active = xs  # [B,N], [B]
            nxt = jax.scipy.special.logsumexp(
                alpha[:, :, None] + w[None, :, :], axis=1) + em_t
            alpha = jnp.where(active[:, None], nxt, alpha)
            return alpha, None

        ts = jnp.arange(1, t)
        active = ts[None, :] < ln[:, None]  # [B, T-1]
        alpha, _ = jax.lax.scan(
            step, alpha0,
            (jnp.moveaxis(em[:, 1:, :], 1, 0), jnp.moveaxis(active, 1, 0)))
        logz = jax.scipy.special.logsumexp(alpha + end[None, :], axis=-1)

        # ---- gold path score -------------------------------------------
        pos = jnp.arange(t)[None, :]
        valid = pos < ln[:, None]  # [B, T]
        em_score = jnp.where(
            valid, jnp.take_along_axis(em, lab[:, :, None],
                                       axis=2)[:, :, 0], 0.0).sum(-1)
        prev, cur = lab[:, :-1], lab[:, 1:]
        trans_valid = pos[:, 1:] < ln[:, None]
        trans_score = jnp.where(trans_valid, w[prev, cur], 0.0).sum(-1)
        last = jnp.take_along_axis(
            lab, jnp.maximum(ln - 1, 0)[:, None], axis=1)[:, 0]
        score = (em_score + trans_score + start[lab[:, 0]] + end[last])
        return (logz - score)[:, None]

    return dispatch(f, emission, transition, label, length, nondiff=(2, 3))


def crf_decoding(emission, transition, label=None, length=None, name=None):
    """Viterbi decode (`crf_decoding` op).  Returns the best path [B, T]
    int64 (zeros past each length); when `label` is given, returns the
    reference's correctness indicator instead: 1 where the decoded tag
    equals the label (within length), else 0."""
    has_label = label is not None

    def f(em, trans, *rest):
        b, t, n = em.shape
        start, end, w = trans[0], trans[1], trans[2:]
        em = em.astype(jnp.float32)
        if length is not None:
            ln = rest[-1].astype(jnp.int32)
        else:
            ln = jnp.full((b,), t, jnp.int32)

        alpha0 = start[None, :] + em[:, 0, :]

        def step(alpha, xs):
            em_t, active = xs
            scores = alpha[:, :, None] + w[None, :, :]  # [B, N, N]
            best_prev = jnp.argmax(scores, axis=1)      # [B, N]
            nxt = jnp.max(scores, axis=1) + em_t
            alpha_new = jnp.where(active[:, None], nxt, alpha)
            # inactive steps back-point to themselves (identity)
            bp = jnp.where(active[:, None], best_prev,
                           jnp.arange(n)[None, :])
            return alpha_new, bp

        ts = jnp.arange(1, t)
        active = ts[None, :] < ln[:, None]
        alpha, bps = jax.lax.scan(
            step, alpha0,
            (jnp.moveaxis(em[:, 1:, :], 1, 0), jnp.moveaxis(active, 1, 0)))
        # bps: [T-1, B, N]
        last_tag = jnp.argmax(alpha + end[None, :], axis=-1)  # [B]

        def back(tag, bp):
            prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
            return prev, tag

        # reverse scan emits the tag at time k+1 for k = 0..t-2 and leaves
        # the time-0 tag in the carry
        first_tag, path_rev = jax.lax.scan(back, last_tag, bps,
                                           reverse=True)
        path = jnp.concatenate([first_tag[None], path_rev], axis=0)
        path = jnp.moveaxis(path, 0, 1)  # [B, T]
        valid = jnp.arange(t)[None, :] < ln[:, None]
        path = jnp.where(valid, path, 0).astype(jnp.int64)
        if has_label:
            lab = rest[0].astype(jnp.int64)
            return jnp.where(valid, (lab == path).astype(jnp.int64), 0)
        return path

    args = (emission, transition) + ((label,) if has_label else ()) + \
        ((length,) if length is not None else ())
    return dispatch(f, *args, nondiff=tuple(range(2, 2 + len(args) - 2)))
