"""Static analysis passes over the repo's own source (AST-level).

Four pass families, each enforcing a serving-stack invariant that is
otherwise only prose in a docstring:

* **trace-hazard** (`TraceHazardPass`) — inside every function handed
  to ``jax.jit`` (directly, through ``functools.partial``, or wrapped
  in `inference.serving._JitTracker`): Python control flow
  (``if``/``while``/ternary/``assert``) on a traced value,
  ``bool()``/``int()``/``float()`` coercions and ``.item()`` on traced
  values — each is a TracerBoolConversionError waiting for the first
  input that changes, or a silent per-call host sync.  Traced-ness is a
  taint walk seeded from the jitted function's positional parameters;
  keyword-only parameters bound by the wrapping ``partial`` (the repo's
  static-argument convention) and ``static_argnums``/``static_argnames``
  are static, and ``.shape``/``.dtype``-style attribute reads launder
  taint (shapes are trace-time constants).
* **flags-in-trace** (same pass) — ``FLAGS_*`` reads
  (``flags.flag(...)`` or ``FLAGS_x`` names) inside a traced function
  bake the flag value read at TRACE time into the executable; a later
  ``set_flags`` is silently ignored for cached signatures (the PR 1
  review-fix class).
* **lock-discipline** (`LockDisciplinePass`) — writes to the known
  shared registries (observability series, ``serving._STATS``,
  dispatch stats, the span buffer) must happen inside ``with <the
  designated lock>``.  Per-module `LockRule`s name the guarded roots,
  the lock spellings, and the alias edges (``s = _stats_for(op)``,
  ``for s in self._series.values()``) through which guarded state
  escapes into locals.
* **engine-mutation** (`EngineMutationPass`) — `DecodeEngine` is
  single-threaded by contract: every mutation happens between steps on
  the driver.  Calls of mutating engine methods (and attribute stores
  on an engine receiver) outside the sanctioned between-steps sites
  are findings.
* **donation** (`DonationPass`) — every ``jax.jit`` site is
  cross-checked: all ``*_pages`` pool parameters of the jitted
  function — and the ``*_scales`` quant-scale arrays that count as
  pool state under FLAGS_kv_quant — must appear in ``donate_argnums``
  (a missed donation means a full extra copy of the KV pool, or a
  silently copied scale buffer, per step).
* **fleet-trace** (`FleetTracePass`) — every HTTP site under the
  fleet plane (a client leg calling ``urlopen``, or a ``do_*``
  server-handler method) must carry the fleet trace: reference the
  ``x-paddle-trace`` plumbing (``fleettrace`` / ``TRACE_HEADER`` /
  the literal header string) in its body or same-module call
  closure, or sit on the explicit allowlist of control-plane
  endpoints with no request identity — so a new fleet endpoint
  cannot silently drop the trace (docs/FLEET_TRACING.md).

Findings carry a content-addressed ``fingerprint`` (pass id + file +
source line text, no line number) so the baseline grandfather file
survives unrelated edits but resurfaces the moment the offending line
changes.  A line ending in ``# tracecheck: ok`` is suppressed — for
the rare deliberate exception; prefer fixing.

All passes are pure ``ast`` — no jax import, no execution of the
scanned code.
"""
from __future__ import annotations

import ast
import hashlib
import os
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Finding", "SourceModule", "LockRule", "EngineRule",
    "FleetTraceRule", "scan_paths",
    "TraceHazardPass", "LockDisciplinePass", "EngineMutationPass",
    "DonationPass", "FleetTracePass", "run_passes",
]


# ---------------------------------------------------------------------------
# Findings
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class Finding:
    pass_id: str   # trace-hazard | flags-in-trace | lock-discipline |
    #              # engine-mutation | donation
    path: str      # repo-relative, forward slashes
    line: int
    message: str
    snippet: str = ""
    # occurrence index among findings with identical (pass, path,
    # snippet) — assigned by `run_passes` in line order, so a NEWLY
    # duplicated copy of a baselined bad line gets a fresh fingerprint
    # instead of silently riding the grandfather entry
    ordinal: int = 0

    @property
    def fingerprint(self) -> str:
        """Content-addressed id for the baseline: stable across line-
        number drift, invalidated when the offending line's text (or
        the message, for file-level findings) changes or when a new
        duplicate of the same line appears."""
        h = hashlib.sha1()
        key = f"{self.pass_id}|{self.path}|{self.snippet or self.message}"
        if self.ordinal:
            key += f"|#{self.ordinal}"
        h.update(key.encode())
        return h.hexdigest()[:16]

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.pass_id}] "
                f"{self.message}")


class SourceModule:
    """One parsed source file plus the line-level suppression map."""

    def __init__(self, abspath: str, relpath: str):
        self.abspath = abspath
        self.relpath = relpath.replace(os.sep, "/")
        with open(abspath, "r", encoding="utf-8") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=relpath)
        # every FunctionDef/Lambda in the module (nested included),
        # name -> node; the jit-site resolver consults this first and
        # the cross-module index second
        self.functions: Dict[str, ast.AST] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.setdefault(node.name, node)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def suppressed(self, lineno: int) -> bool:
        return "# tracecheck: ok" in self.line_text(lineno)

    def finding(self, pass_id: str, node: ast.AST, message: str
                ) -> Optional[Finding]:
        line = getattr(node, "lineno", 0)
        if self.suppressed(line):
            return None
        return Finding(pass_id, self.relpath, line, message,
                       snippet=self.line_text(line))


def scan_paths(paths: Sequence[str], repo_root: str) -> List[SourceModule]:
    """Parse every ``.py`` file under ``paths`` (files or directories,
    absolute or repo-root-relative), sorted for determinism."""
    files = []
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(repo_root, p)
        if os.path.isfile(ap):
            files.append(ap)
        elif os.path.isdir(ap):
            for dirpath, _dirs, names in os.walk(ap):
                if "__pycache__" in dirpath:
                    continue
                for n in sorted(names):
                    if n.endswith(".py"):
                        files.append(os.path.join(dirpath, n))
        else:
            raise FileNotFoundError(f"no such path: {p}")
    mods = []
    for ap in sorted(dict.fromkeys(files)):
        rel = os.path.relpath(ap, repo_root)
        mods.append(SourceModule(ap, rel))
    return mods


# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------
def _dotted(node: ast.AST) -> Optional[str]:
    """`a.b.c` -> "a.b.c" for Name/Attribute chains, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return None if base is None else f"{base}.{node.attr}"
    return None


def _qualname_walk(tree: ast.AST):
    """Yield (qualname, FunctionDef) for every function in the module,
    with ``Class.method`` / ``outer.<locals>.inner`` qualnames."""
    def rec(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                yield q, child
                yield from rec(child, f"{q}.<locals>.")
            elif isinstance(child, ast.ClassDef):
                yield from rec(child, f"{prefix}{child.name}.")
            else:
                yield from rec(child, prefix)
    yield from rec(tree, "")


# ---------------------------------------------------------------------------
# jit-site collection (shared by the trace and donation passes)
# ---------------------------------------------------------------------------
@dataclass
class JitSite:
    call: ast.Call            # the jax.jit(...) call
    module: SourceModule
    fn_node: Optional[ast.AST]        # resolved FunctionDef / Lambda
    fn_name: str
    static_names: Tuple[str, ...]     # partial kwargs + static_argnames
    static_argnums: Tuple[int, ...]
    pos_shift: int                    # partial positional args bound
    donate_argnums: Optional[Tuple[int, ...]]  # None = kwarg absent


def _is_jax_jit(func: ast.AST) -> bool:
    d = _dotted(func)
    return d is not None and (d == "jax.jit" or d.endswith(".jax.jit")
                              or d == "jit")


def _is_jit_tracker(func: ast.AST) -> bool:
    # inference.serving._JitTracker owns its own jax.jit (single source
    # of truth for donate_argnums), so a tracker construction over a
    # plain callable IS a jit site
    d = _dotted(func)
    return d is not None and d.split(".")[-1] == "_JitTracker"


def _literal_ints(node: ast.AST) -> Optional[Tuple[int, ...]]:
    try:
        v = ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None
    if isinstance(v, int):
        return (v,)
    if isinstance(v, (tuple, list)) and all(isinstance(x, int) for x in v):
        return tuple(v)
    return None


def collect_jit_sites(modules: Sequence[SourceModule]) -> List[JitSite]:
    index: Dict[str, Tuple[SourceModule, ast.AST]] = {}
    for m in modules:
        for name, node in m.functions.items():
            index.setdefault(name, (m, node))
    sites = []
    for m in modules:
        for node in ast.walk(m.tree):
            if not (isinstance(node, ast.Call) and node.args
                    and (_is_jax_jit(node.func)
                         or _is_jit_tracker(node.func))):
                continue
            fn_expr = node.args[0]
            if _is_jit_tracker(node.func) and \
                    isinstance(fn_expr, ast.Call) and \
                    _is_jax_jit(fn_expr.func):
                continue  # tracker over an explicit jax.jit: the inner
                #         # call is collected as its own site
            static_names: List[str] = []
            static_argnums: Tuple[int, ...] = ()
            pos_shift = 0
            donate = None
            for kw in node.keywords:
                if kw.arg == "donate_argnums":
                    donate = _literal_ints(kw.value)
                elif kw.arg == "static_argnums":
                    static_argnums = _literal_ints(kw.value) or ()
                elif kw.arg == "static_argnames":
                    try:
                        v = ast.literal_eval(kw.value)
                        static_names.extend(
                            [v] if isinstance(v, str) else list(v))
                    except (ValueError, SyntaxError):
                        pass
            # unwrap functools.partial(fn, *bound, **statics)
            target = fn_expr
            if isinstance(target, ast.Call) and \
                    (_dotted(target.func) or "").endswith("partial") and \
                    target.args:
                pos_shift = len(target.args) - 1
                static_names.extend(
                    kw.arg for kw in target.keywords if kw.arg)
                target = target.args[0]
            fn_node = None
            fn_name = "<unknown>"
            if isinstance(target, ast.Lambda):
                fn_node, fn_name = target, "<lambda>"
            else:
                d = _dotted(target)
                if d is not None:
                    fn_name = d.split(".")[-1]
                    if fn_name in m.functions:
                        fn_node = m.functions[fn_name]
                    elif fn_name in index:
                        fn_node = index[fn_name][1]
            sites.append(JitSite(node, m, fn_node, fn_name,
                                 tuple(static_names), static_argnums,
                                 pos_shift, donate))
    return sites


# ---------------------------------------------------------------------------
# trace-hazard + flags-in-trace
# ---------------------------------------------------------------------------
# attribute reads that launder taint: trace-time constants of a traced
# array
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "weak_type", "sharding"}
# len() is deliberately absent: on a traced array of known rank it
# returns the STATIC shape[0] — legal jax, no sync, no trace error
_COERCIONS = {"bool", "int", "float", "complex"}


class TraceHazardPass:
    """Hazard walk over every resolved jit-site function body."""

    def run(self, modules: Sequence[SourceModule],
            sites: Optional[List[JitSite]] = None) -> List[Finding]:
        out: List[Finding] = []
        seen = set()
        for site in sites if sites is not None \
                else collect_jit_sites(modules):
            fn = site.fn_node
            if fn is None:
                continue
            # dedup on the EFFECTIVE trace config, not just the def:
            # the same function jitted twice with different static
            # bindings has different traced parameter sets, and each
            # must be analyzed
            key = (site.module.relpath, id(fn), site.static_names,
                   site.static_argnums, site.pos_shift)
            if key in seen:
                continue
            seen.add(key)
            out.extend(self._check_fn(site))
        return [f for f in out if f is not None]

    def _check_fn(self, site: JitSite) -> List[Finding]:
        fn = site.fn_node
        mod = site.module
        args = fn.args
        tainted = set()
        params = [a.arg for a in getattr(args, "posonlyargs", [])] + \
            [a.arg for a in args.args]
        for i, name in enumerate(params):
            if name in site.static_names:
                continue
            # static_argnums index the JITTED signature: def param i is
            # jit argument i - (partial-bound positional count)
            if (i - site.pos_shift) in site.static_argnums:
                continue
            if name == "self":
                continue
            tainted.add(name)
        if args.vararg is not None:
            tainted.add(args.vararg.arg)
        for a in args.kwonlyargs:
            # keyword-only params bound by the wrapping partial (or
            # named in static_argnames) are static; the rest are traced
            # runtime kwargs
            if a.arg not in site.static_names:
                tainted.add(a.arg)

        findings: List[Finding] = []

        def is_tainted(e: ast.AST) -> bool:
            if isinstance(e, ast.Name):
                return e.id in tainted
            if isinstance(e, ast.Attribute):
                if e.attr in _STATIC_ATTRS:
                    return False
                return is_tainted(e.value)
            if isinstance(e, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                return False
            for child in ast.iter_child_nodes(e):
                if is_tainted(child):
                    return True
            return False

        def taint_target(t: ast.AST):
            if isinstance(t, ast.Name):
                tainted.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    taint_target(e)
            elif isinstance(t, ast.Starred):
                taint_target(t.value)
            elif isinstance(t, ast.Subscript):
                # storing a traced value into a container taints the
                # container (vals[p] = traced_v)
                taint_target(t.value)
            # attribute stores on locals: ignore (rare in pure fns)

        def flag_read(call: ast.Call) -> bool:
            d = _dotted(call.func)
            return d is not None and (d == "flag" or d.endswith(".flag"))

        def visit(node: ast.AST):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                return  # nested defs have their own scope/trace story
            if isinstance(node, (ast.If, ast.While)) and \
                    is_tainted(node.test):
                findings.append(mod.finding(
                    "trace-hazard", node,
                    f"python `{type(node).__name__.lower()}` on a traced "
                    f"value inside jitted `{site.fn_name}` — branch on "
                    f"host data or use lax.cond/jnp.where"))
            elif isinstance(node, ast.IfExp) and is_tainted(node.test):
                findings.append(mod.finding(
                    "trace-hazard", node,
                    f"conditional expression on a traced value inside "
                    f"jitted `{site.fn_name}` — use jnp.where"))
            elif isinstance(node, ast.Assert) and is_tainted(node.test):
                findings.append(mod.finding(
                    "trace-hazard", node,
                    f"assert on a traced value inside jitted "
                    f"`{site.fn_name}` — traced assertions do not run; "
                    f"use checkify or move the check to the host"))
            elif isinstance(node, ast.comprehension):
                for cond in node.ifs:
                    if is_tainted(cond):
                        findings.append(mod.finding(
                            "trace-hazard", cond,
                            f"comprehension filter on a traced value "
                            f"inside jitted `{site.fn_name}`"))
            elif isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d in _COERCIONS and any(is_tainted(a)
                                           for a in node.args):
                    findings.append(mod.finding(
                        "trace-hazard", node,
                        f"host coercion `{d}()` on a traced value inside "
                        f"jitted `{site.fn_name}` — forces a trace error "
                        f"or a per-call host sync"))
                elif isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "item" and \
                        is_tainted(node.func.value):
                    findings.append(mod.finding(
                        "trace-hazard", node,
                        f"`.item()` on a traced value inside jitted "
                        f"`{site.fn_name}` — blocking host sync"))
                elif flag_read(node):
                    findings.append(mod.finding(
                        "flags-in-trace", node,
                        f"flag read inside jitted `{site.fn_name}` bakes "
                        f"the trace-time value into the executable — "
                        f"read the flag on the host and pass it in (or "
                        f"key the executable cache on it)"))
            elif isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load) and \
                    node.id.startswith("FLAGS_"):
                findings.append(mod.finding(
                    "flags-in-trace", node,
                    f"FLAGS read `{node.id}` inside jitted "
                    f"`{site.fn_name}` bakes the trace-time value into "
                    f"the executable"))
            # statement-order taint propagation
            if isinstance(node, ast.Assign):
                if is_tainted(node.value):
                    for t in node.targets:
                        taint_target(t)
            elif isinstance(node, ast.AugAssign):
                if is_tainted(node.value):
                    taint_target(node.target)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                if is_tainted(node.value):
                    taint_target(node.target)
            elif isinstance(node, ast.For):
                if is_tainted(node.iter):
                    taint_target(node.target)
            elif isinstance(node, (ast.NamedExpr,)):
                if is_tainted(node.value):
                    taint_target(node.target)
            for child in ast.iter_child_nodes(node):
                visit(child)

        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            visit(stmt)
        return findings


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------
_MUTATORS = {
    "append", "appendleft", "add", "clear", "discard", "extend",
    "insert", "pop", "popitem", "popleft", "remove", "setdefault",
    "update", "move_to_end", "sort", "reverse",
}


@dataclass
class LockRule:
    """Per-module lock-discipline spec: which names are the guarded
    shared registries, which lock spellings guard them, and how guarded
    state aliases into locals."""

    locks: Tuple[str, ...]             # acceptable `with X:` spellings
    roots: Tuple[str, ...] = ()        # module-global registry names
    self_attrs: Tuple[str, ...] = ()   # guarded `self.<attr>` state
    alias_fns: Tuple[str, ...] = ()    # x = alias_fn(...) taints x
    alias_attrs: Tuple[str, ...] = ()  # x = y.<attr> taints x
    guarded_classes: Tuple[str, ...] = ()  # self.<any> writes in these
    #                                  # classes must be locked
    exempt: Tuple[str, ...] = ()       # exempt function qualnames


class LockDisciplinePass:
    def __init__(self, rules: Dict[str, LockRule]):
        # rules keyed by module-path suffix ("observability/metrics.py")
        self.rules = rules

    def _rule_for(self, relpath: str) -> Optional[LockRule]:
        for suffix, rule in self.rules.items():
            if relpath.endswith(suffix):
                return rule
        return None

    def run(self, modules: Sequence[SourceModule]) -> List[Finding]:
        out: List[Finding] = []
        for m in modules:
            rule = self._rule_for(m.relpath)
            if rule is None:
                continue
            for qualname, fn in _qualname_walk(m.tree):
                if qualname in rule.exempt or \
                        qualname.endswith("__init__"):
                    continue
                out.extend(self._check_fn(m, rule, qualname, fn))
        return [f for f in out if f is not None]

    def _check_fn(self, mod: SourceModule, rule: LockRule,
                  qualname: str, fn: ast.AST) -> List[Finding]:
        findings: List[Finding] = []
        aliases = set()
        in_guarded_class = any(qualname.startswith(c + ".")
                               for c in rule.guarded_classes)

        def guarded(e: ast.AST) -> bool:
            """Does this expression reach guarded shared state?"""
            if isinstance(e, ast.Name):
                return e.id in rule.roots or e.id in aliases
            if isinstance(e, ast.Attribute):
                if isinstance(e.value, ast.Name) and \
                        e.value.id == "self" and e.attr in rule.self_attrs:
                    return True
                if e.attr in rule.alias_attrs:
                    return True
                return guarded(e.value)
            if isinstance(e, ast.Call):
                d = _dotted(e.func)
                if d is not None and d.split(".")[-1] in rule.alias_fns:
                    return True
                return guarded(e.func)
            if isinstance(e, ast.Subscript):
                return guarded(e.value)
            return False

        def lock_expr(item: ast.AST) -> bool:
            d = _dotted(item)
            return d is not None and (
                d in rule.locks or d.split(".")[-1] in rule.locks)

        def report(node, what):
            findings.append(mod.finding(
                "lock-discipline", node,
                f"{what} outside `with "
                f"{'/'.join(rule.locks)}` in `{qualname}` — shared "
                f"telemetry state must only be written under its "
                f"designated lock"))

        def visit(node: ast.AST, locked: bool):
            if isinstance(node, ast.With):
                inner = locked or any(lock_expr(i.context_expr)
                                      for i in node.items)
                for child in node.body:
                    visit(child, inner)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                return  # nested defs get their own qualname walk
            # alias propagation (runs regardless of lock state: an
            # alias taken under the lock can leak out of it)
            if isinstance(node, ast.Assign) and guarded(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        aliases.add(t.id)
            elif isinstance(node, ast.For) and guarded(node.iter):
                if isinstance(node.target, ast.Name):
                    aliases.add(node.target.id)
                elif isinstance(node.target, ast.Tuple):
                    for e in node.target.elts:
                        if isinstance(e, ast.Name):
                            aliases.add(e.id)
            if not locked:
                if isinstance(node, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in targets:
                        if isinstance(t, ast.Name) and t.id in rule.roots:
                            report(node, f"rebinding of shared registry "
                                         f"`{t.id}`")
                        elif isinstance(t, ast.Subscript) and \
                                guarded(t.value):
                            report(node, "item write to shared registry "
                                         "state")
                        elif isinstance(t, ast.Attribute):
                            if guarded(t.value):
                                report(node, "attribute write to shared "
                                             "registry state")
                            elif in_guarded_class and \
                                    isinstance(t.value, ast.Name) and \
                                    t.value.id == "self":
                                report(node, "unlocked mutation of "
                                             "lock-guarded object state")
                        elif isinstance(t, ast.Tuple):
                            for e in t.elts:
                                if isinstance(e, ast.Name) and \
                                        e.id in rule.roots:
                                    report(node, f"rebinding of shared "
                                                 f"registry `{e.id}`")
                elif isinstance(node, ast.Delete):
                    for t in node.targets:
                        if isinstance(t, ast.Subscript) and \
                                guarded(t.value):
                            report(node, "item delete on shared registry "
                                         "state")
                elif isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr in _MUTATORS and \
                        guarded(node.func.value):
                    report(node, f"mutating call "
                                 f"`.{node.func.attr}()` on shared "
                                 f"registry state")
            for child in ast.iter_child_nodes(node):
                visit(child, locked)

        for stmt in fn.body:
            visit(stmt, False)
        return findings


# ---------------------------------------------------------------------------
# engine-mutation discipline
# ---------------------------------------------------------------------------
@dataclass
class EngineRule:
    """Which methods mutate a DecodeEngine, which receiver spellings
    count as "an engine", and which (module-suffix -> qualname
    prefixes) sites are sanctioned between-steps callers ("*" = the
    whole module)."""

    mutators: Tuple[str, ...]
    receivers: Tuple[str, ...] = ("eng", "engine", "self.engine",
                                  "self._engine")
    sanctioned: Dict[str, Tuple[str, ...]] = field(default_factory=dict)


class EngineMutationPass:
    def __init__(self, rule: EngineRule):
        self.rule = rule

    def _sanctioned(self, relpath: str, qualname: str) -> bool:
        for suffix, prefixes in self.rule.sanctioned.items():
            if relpath.endswith(suffix):
                if "*" in prefixes:
                    return True
                return any(qualname == p or qualname.startswith(p)
                           for p in prefixes)
        return False

    @staticmethod
    def _own_nodes(fn: ast.AST):
        """Every node lexically inside ``fn`` but NOT inside a nested
        def (those are analyzed under their own qualname)."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def run(self, modules: Sequence[SourceModule]) -> List[Finding]:
        out: List[Finding] = []
        rule = self.rule
        for m in modules:
            for qualname, fn in _qualname_walk(m.tree):
                if self._sanctioned(m.relpath, qualname):
                    continue
                for node in self._own_nodes(fn):
                    if isinstance(node, ast.Call) and \
                            isinstance(node.func, ast.Attribute) and \
                            node.func.attr in rule.mutators and \
                            _dotted(node.func.value) in rule.receivers:
                        f = m.finding(
                            "engine-mutation", node,
                            f"engine-mutating call "
                            f"`.{node.func.attr}()` from unsanctioned "
                            f"site `{qualname}` — all engine mutation "
                            f"must happen between steps on the driver "
                            f"(see inference/frontend.py)")
                        if f:
                            out.append(f)
                    elif isinstance(node, ast.Assign):
                        for t in node.targets:
                            if isinstance(t, ast.Attribute) and \
                                    _dotted(t.value) in rule.receivers:
                                f = m.finding(
                                    "engine-mutation", node,
                                    f"engine attribute store "
                                    f"`.{t.attr} = ...` from "
                                    f"unsanctioned site `{qualname}`")
                                if f:
                                    out.append(f)
        return out


# ---------------------------------------------------------------------------
# donation coverage
# ---------------------------------------------------------------------------
class DonationPass:
    """Every jax.jit site whose function carries KV-pool parameters —
    ``*_pages`` page pools AND the ``*_scales`` quant-scale arrays
    that live beside them (FLAGS_kv_quant) — must donate ALL of them.
    The scale arrays are pool state: a jit site donating the pages but
    copying the scales would silently pay (and leak) a per-step scale
    buffer, and under FLAGS_sanitize the tombstoned and live sets
    would diverge."""

    def run(self, modules: Sequence[SourceModule],
            sites: Optional[List[JitSite]] = None) -> List[Finding]:
        out: List[Finding] = []
        for site in sites if sites is not None \
                else collect_jit_sites(modules):
            fn = site.fn_node
            if fn is None or isinstance(fn, ast.Lambda):
                continue
            args = fn.args
            params = [a.arg for a in getattr(args, "posonlyargs", [])] + \
                [a.arg for a in args.args]
            pages = [(i, n) for i, n in enumerate(params)
                     if n.endswith("_pages") or n.endswith("_scales")]
            if not pages:
                continue
            donated = set(site.donate_argnums or ())
            for i, name in pages:
                jit_idx = i - site.pos_shift
                if jit_idx < 0:
                    continue  # bound by partial positionally: not a
                    #         # jit argument at all
                if jit_idx not in donated:
                    f = site.module.finding(
                        "donation", site.call,
                        f"jax.jit of `{site.fn_name}` does not donate "
                        f"pool parameter `{name}` (argnum {jit_idx}) — "
                        f"add it to donate_argnums or the step pays a "
                        f"full extra copy of the KV pool"
                        + ("" if site.donate_argnums is not None
                           else " (no donate_argnums at all)"))
                    if f:
                        out.append(f)
        return out


# ---------------------------------------------------------------------------
# fleet-trace propagation
# ---------------------------------------------------------------------------
@dataclass
class FleetTraceRule:
    """Which modules are the fleet's network plane, which spellings
    count as carrying the trace, and which HTTP sites are exempt
    (control-plane endpoints with no request identity)."""

    path_markers: Tuple[str, ...] = ("fleet/",)   # relpath substring
    trace_names: Tuple[str, ...] = ("fleettrace", "TRACE_HEADER")
    trace_literal: str = "x-paddle-trace"
    allowlist: Tuple[str, ...] = ()               # exact qualnames


class FleetTracePass:
    """Every HTTP site under the fleet plane must carry the fleet
    trace (docs/FLEET_TRACING.md): a client leg (any function calling
    ``urlopen``) or a server handler (``do_*`` method) either
    references the trace plumbing — ``fleettrace``, ``TRACE_HEADER``,
    or the literal ``x-paddle-trace`` string — in its body or its
    same-module call closure, or sits on the explicit allowlist.  A
    new fleet endpoint that silently drops the trace flags the moment
    it is written."""

    def __init__(self, rule: FleetTraceRule):
        self.rule = rule

    def _in_scope(self, relpath: str) -> bool:
        return any(mark in relpath for mark in self.rule.path_markers)

    @staticmethod
    def _site_kind(fn: ast.AST) -> Optional[str]:
        if getattr(fn, "name", "").startswith("do_"):
            return "HTTP handler"
        for node in EngineMutationPass._own_nodes(fn):
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d is not None and d.split(".")[-1] == "urlopen":
                    return "HTTP client leg"
        return None

    def _carries_trace(self, fn: ast.AST, mod: SourceModule,
                       visited: Optional[set] = None) -> bool:
        """Body or same-module transitive call closure references the
        trace plumbing.  The closure walk matters: ``do_POST``
        dispatches to ``_generate`` which reads the header — the
        handler itself never spells the name."""
        if visited is None:
            visited = set()
        if id(fn) in visited:
            return False
        visited.add(id(fn))
        called: List[str] = []
        for node in EngineMutationPass._own_nodes(fn):
            if isinstance(node, ast.Name) and \
                    node.id in self.rule.trace_names:
                return True
            if isinstance(node, ast.Attribute) and \
                    node.attr in self.rule.trace_names:
                return True
            if isinstance(node, ast.Constant) and \
                    node.value == self.rule.trace_literal:
                return True
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d is not None:
                    called.append(d.split(".")[-1])
        for name in called:
            target = mod.functions.get(name)
            if target is not None and \
                    self._carries_trace(target, mod, visited):
                return True
        return False

    def run(self, modules: Sequence[SourceModule]) -> List[Finding]:
        out: List[Finding] = []
        for m in modules:
            if not self._in_scope(m.relpath):
                continue
            for qualname, fn in _qualname_walk(m.tree):
                kind = self._site_kind(fn)
                if kind is None:
                    continue
                if qualname in self.rule.allowlist:
                    continue
                if self._carries_trace(fn, m):
                    continue
                f = m.finding(
                    "fleet-trace", fn,
                    f"{kind} `{qualname}` neither propagates the fleet "
                    f"trace (x-paddle-trace / fleettrace.TRACE_HEADER) "
                    f"nor sits on the control-plane allowlist — fleet "
                    f"HTTP surfaces must carry the trace or be "
                    f"explicitly exempted (docs/FLEET_TRACING.md)")
                if f:
                    out.append(f)
        return out


# ---------------------------------------------------------------------------
# combined runner
# ---------------------------------------------------------------------------
def run_passes(modules: Sequence[SourceModule],
               lock_rules: Optional[Dict[str, LockRule]] = None,
               engine_rule: Optional[EngineRule] = None,
               fleet_rule: Optional[FleetTraceRule] = None
               ) -> List[Finding]:
    findings: List[Finding] = []
    sites = collect_jit_sites(modules)  # shared: one AST walk, 2 users
    findings.extend(TraceHazardPass().run(modules, sites))
    if lock_rules:
        findings.extend(LockDisciplinePass(lock_rules).run(modules))
    if engine_rule:
        findings.extend(EngineMutationPass(engine_rule).run(modules))
    findings.extend(DonationPass().run(modules, sites))
    if fleet_rule:
        findings.extend(FleetTracePass(fleet_rule).run(modules))
    findings.sort(key=lambda f: (f.path, f.line, f.pass_id))
    seen: Dict[tuple, int] = {}
    out = []
    for f in findings:
        key = (f.pass_id, f.path, f.snippet or f.message)
        n = seen.get(key, 0)
        seen[key] = n + 1
        out.append(replace(f, ordinal=n) if n else f)
    return out
