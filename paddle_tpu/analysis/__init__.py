"""Static trace-safety / donation / lock-discipline analysis + the
runtime sanitizer for the serving stack.

Two halves:

* `passes` — AST lint passes over the repo's own source (no code is
  executed, no jax import).  `tools/tracecheck.py` is the CLI;
  `run_tracecheck()` is the library entry.  The **repo spec** below
  names the designated locks, guarded registries, engine-mutation
  sanction sites, the fleet-trace control-plane allowlist, and the
  default scan targets — the invariants the serving stack's
  docstrings promise, made machine-checkable.
* `sanitizer` — runtime mode (``FLAGS_sanitize``): donated-buffer
  tombstones with use-after-donate errors naming the donation site,
  lock-order cycle detection over the designated locks, warm retraces
  raising instead of counting, a host-sync sentinel, and the
  `KVBlockPool.assert_consistent` audit every engine step.

Baseline workflow: ``tracecheck --write-baseline`` grandfathers the
current findings into a JSON file keyed by content fingerprint (pass +
file + source-line text), so pre-existing debt never blocks CI while
any TOUCHED line resurfaces immediately.  The shipped baseline
(`tools/tracecheck_baseline.json`) is empty: every finding the passes
surfaced was fixed, not grandfathered.

See docs/STATIC_ANALYSIS.md for the pass catalog and workflow.
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from .passes import (  # noqa: F401
    DonationPass, EngineMutationPass, EngineRule, Finding,
    FleetTracePass, FleetTraceRule, LockRule,
    LockDisciplinePass, SourceModule, TraceHazardPass, run_passes,
    scan_paths,
)
from . import sanitizer  # noqa: F401

__all__ = [
    "Finding", "LockRule", "EngineRule", "FleetTraceRule",
    "SourceModule",
    "TraceHazardPass", "LockDisciplinePass", "EngineMutationPass",
    "DonationPass", "FleetTracePass", "run_passes", "scan_paths",
    "run_tracecheck",
    "REPO_LOCK_RULES", "REPO_ENGINE_RULE", "REPO_FLEET_TRACE_RULE",
    "DEFAULT_TARGETS",
    "load_baseline", "write_baseline", "split_baselined", "sanitizer",
]


# ---------------------------------------------------------------------------
# The repo spec: designated locks, guarded registries, sanction sites.
# This is the machine-readable form of the serving stack's concurrency
# contracts — keep it in sync with the module docstrings it encodes.
# ---------------------------------------------------------------------------
REPO_LOCK_RULES: Dict[str, LockRule] = {
    # ONE telemetry lock: every registry series mutation and every
    # serving._STATS read-modify-write happens under observability.LOCK
    "observability/metrics.py": LockRule(
        locks=("LOCK",),
        roots=("_state",),
        self_attrs=("_series", "_metrics", "_views"),
    ),
    "observability/tracing.py": LockRule(
        locks=("_lock",),
        roots=("_spans", "_dropped"),
    ),
    # flight recorder: every CROSS-THREAD surface — the sealed-record
    # ring, the window totals, the goodput counters — mutates under
    # the module's designated lock (statusz and dump read from
    # arbitrary threads while the engine thread appends).  The OPEN
    # record (`_cur`'s contents) is deliberately engine-thread-private
    # and lock-free, so it is not listed here.
    "observability/flight.py": LockRule(
        locks=("_lock",),
        self_attrs=("_ring", "_win_tokens", "_win_time",
                    "_fin_total", "_fin_met", "dumps"),
    ),
    "observability/reporter.py": LockRule(
        locks=("_lock",),
        roots=("_thread", "_stop"),
    ),
    # cost observatory: the process-global profile table and every
    # CostModel's calibration/error tables mutate under the module's
    # designated lock (statusz renders them from arbitrary threads).
    # The per-step `_pending` prediction is engine-thread-private like
    # the flight recorder's open record and deliberately unlisted.
    "observability/costmodel.py": LockRule(
        locks=("_lock",),
        roots=("_PROFILES", "_forced_engines"),
        self_attrs=("_calib", "_err"),
    ),
    # alert engine: the per-rule state table and the transitions list
    # (/alertz reads them from the ops server's handler threads while
    # the engine thread evaluates) mutate under the module's
    # designated lock.  Per-rule evaluation HISTORIES are engine-
    # thread-private like the flight recorder's open record and
    # deliberately unlisted.
    "observability/alerts.py": LockRule(
        locks=("_lock",),
        self_attrs=("_state", "_transitions"),
    ),
    # ops-plane registry: engine/frontend registration and the server
    # handle swap mutate under the module lock (handlers snapshot
    # under it and render outside it)
    "observability/opsserver.py": LockRule(
        locks=("_lock",),
        roots=("_ENGINES", "_FRONTENDS", "_SERVER"),
    ),
    # profiling plane: capture state, the device-time table and the
    # measured-MFU/drift tables mutate under the module's designated
    # lock (/profilez and request_capture touch them from arbitrary
    # threads).  The open-step probe dict (_probe/_probe_now) is
    # engine-thread-private like the flight recorder's open record
    # and deliberately unlisted.
    "observability/profiling.py": LockRule(
        locks=("_lock",),
        roots=("_PROFILERS", "_forced_engines"),
        self_attrs=("_capture_pending", "_capture_remaining",
                    "_capture_total", "_captures", "_device_s",
                    "_host_ratio", "_mfu", "_dev_calib", "_drift"),
    ),
    "inference/serving.py": LockRule(
        locks=("_TELEMETRY_LOCK", "LOCK"),
        roots=("_STATS",),
    ),
    "inference/speculative.py": LockRule(
        locks=("_TELEMETRY_LOCK", "LOCK"),
        roots=("_STATS",),
    ),
    # dispatch keeps its own two locks: per-op stats under _STATS_LOCK
    # (including the _OpStats objects aliased out of the registry), the
    # executable cache under _CACHE_LOCK
    "core/dispatch.py": LockRule(
        locks=("_STATS_LOCK", "_CACHE_LOCK"),
        roots=("_STATS", "_CACHE"),
        alias_fns=("_stats_for",),
        alias_attrs=("stats",),
        guarded_classes=("_OpStats",),
    ),
}

# DecodeEngine is single-threaded by contract: every mutation happens
# between steps on the driver.  serving.py / speculative.py ARE the
# engine; resilience.py is the containment ladder + crash recovery
# (engine-called between steps, and recovery mutates the engine
# between steps BY DESIGN — the fold/re-admit in `recover` and the
# bisect-quarantine preempt/retire in `ResilienceManager` are
# sanctioned recovery sites); durability.py is the write-ahead
# journal + fresh-process restore + hung-step watchdog (restore
# re-admits into a just-built idle engine, the watchdog abandons and
# neutralizes a hung one — both sanctioned recovery-class mutation);
# in frontend.py only the schedulers (engine-called, between steps),
# the driver's control-application points, and the driver's recovery
# supervision may mutate.
REPO_ENGINE_RULE = EngineRule(
    mutators=(
        "add_request", "evict", "preempt", "step", "run", "generate",
        "_admit", "_admit_one", "_finish", "_emit", "_bind_slot",
        "_prefill_into", "_cancel_queued", "_cancel_running",
        "_retire_queued", "_grow_block_tables", "_mixed_step",
        "_stamp_admit", "_stamp_first_token", "_on_first_token",
        "_register_prompt_pages", "_register_generated_pages",
        "_debug_check_pool",
        # fault containment / recovery (inference.resilience): the
        # ladder's retry unit, slot quarantine, and admission unwind
        # mutate the engine — callable only from sanctioned sites
        "_step_inner", "_quarantine_slot", "_unwind_failed_admit",
        "_release_slot",
        # durable serving (inference.durability): executable handoff
        # to a rebuilt engine and watchdog abandonment of a hung one
        "adopt_executables", "_abandon_inflight",
        # quantized weight storage (FLAGS_serve_weights=int8): the
        # construction-time fold replacing the engine's f32 matmul
        # leaves with int8+scale pairs — a param-tree mutation no
        # observer (cost model, profiler, alert evaluator) may ever
        # invoke: re-quantizing a live tree would silently re-trace
        # every warm executable
        "_fold_weight_quant",
    ),
    receivers=("eng", "engine", "self.engine", "self._engine"),
    sanctioned={
        "inference/serving.py": ("*",),
        "inference/speculative.py": ("*",),
        "inference/resilience.py": ("*",),
        "inference/durability.py": ("*",),
        "inference/frontend.py": (
            "Scheduler.", "FIFOScheduler.", "SLOScheduler.",
            "ServingFrontend._apply_control", "ServingFrontend._drive",
            "ServingFrontend._recover_engine",
        ),
        # the flight recorder READS engine state (batch composition,
        # pool occupancy, SLO burn) from inside the step — sanctioned
        # for exactly the recorder class so a rogue recorder that
        # MUTATES the engine (the tempting bug: "just retire the slow
        # request from here") still flags
        "observability/flight.py": ("FlightRecorder.",),
        # the cost observatory likewise READS the engine (batch
        # composition for prediction, pool/params for the ledger,
        # the calibration update site scoring sealed records) —
        # sanctioned for exactly the CostModel class, so a rogue cost
        # model that mutates the engine ("just preempt the slot my
        # prediction says is over budget") still flags
        "observability/costmodel.py": ("CostModel.",),
        # the alert evaluator READS the engine between steps (pool
        # pressure, health, burn gauges for its signals) — sanctioned
        # for exactly the AlertEngine class, so a rogue evaluator that
        # mutates the engine ("just preempt the request burning the
        # budget from inside evaluate()") still flags.  The ops
        # server's handlers are NOT sanctioned at all: every endpoint
        # is read-only by contract, and an endpoint that grows a
        # mutating call flags the moment it is written.
        "observability/alerts.py": ("AlertEngine.",),
        # the profiling plane READS the engine (blocking on dispatch
        # outputs, scoring sealed records, the between-steps capture-
        # arming site) — sanctioned for exactly the Profiler class, so
        # a rogue profiler that mutates the engine ("just preempt the
        # slot whose dispatch keeps blocking longest") still flags
        "observability/profiling.py": ("Profiler.",),
    },
)

# Fleet trace propagation (docs/FLEET_TRACING.md): every HTTP site
# under paddle_tpu/fleet/ must carry the x-paddle-trace plumbing or
# sit on this allowlist — control-plane endpoints that carry no
# request identity, so there is nothing to trace:
#   _get_json / _post_json    router's generic JSON fetch/post helpers
#   FleetRouter._fetch_text   /metrics scrape for the /fleetz rollup
#   ReplicaHandle.fetch_info  /v1/info identity card at add_replica
#   ReplicaHandle.poll        /readyz admission poll (its t0/t1/now_ns
#                             FEED the clock sync, but the poll itself
#                             belongs to no request)
#   ReplicaHandle.alertz      /alertz scrape for the fleet rollup
REPO_FLEET_TRACE_RULE = FleetTraceRule(
    path_markers=("paddle_tpu/fleet/",),
    allowlist=(
        "_get_json", "_post_json", "FleetRouter._fetch_text",
        "ReplicaHandle.fetch_info", "ReplicaHandle.poll",
        "ReplicaHandle.alertz",
    ),
)

# What `tools/tracecheck.py` scans by default (repo-root relative):
# the serving stack plus the dispatch cache and the fleet's network
# plane — the modules whose invariants the passes encode.
DEFAULT_TARGETS: Tuple[str, ...] = (
    "paddle_tpu/inference",
    "paddle_tpu/observability",
    "paddle_tpu/core/dispatch.py",
    "paddle_tpu/fleet",
)


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def run_tracecheck(paths: Optional[Sequence[str]] = None,
                   root: Optional[str] = None,
                   lock_rules: Optional[Dict[str, LockRule]] = None,
                   engine_rule: Optional[EngineRule] = None,
                   fleet_rule: Optional[FleetTraceRule] = None
                   ) -> List[Finding]:
    """Run every static pass over ``paths`` (default: the repo's
    serving-stack targets) and return the sorted findings."""
    root = root or repo_root()
    modules = scan_paths(paths or DEFAULT_TARGETS, root)
    return run_passes(
        modules,
        lock_rules=REPO_LOCK_RULES if lock_rules is None else lock_rules,
        engine_rule=REPO_ENGINE_RULE if engine_rule is None
        else engine_rule,
        fleet_rule=REPO_FLEET_TRACE_RULE if fleet_rule is None
        else fleet_rule)


# ---------------------------------------------------------------------------
# Baseline (grandfather) file
# ---------------------------------------------------------------------------
def load_baseline(path: str) -> Dict[str, dict]:
    """fingerprint -> entry dict.  A missing file is an empty
    baseline."""
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    return {e["fingerprint"]: e for e in data.get("entries", [])}


def write_baseline(path: str, findings: Sequence[Finding]):
    entries = [{
        "fingerprint": f.fingerprint,
        "pass": f.pass_id,
        "path": f.path,
        "line": f.line,       # informational; the fingerprint is the key
        "message": f.message,
    } for f in findings]
    with open(path, "w") as fh:
        json.dump({"version": 1, "entries": entries}, fh, indent=2,
                  sort_keys=True)
        fh.write("\n")


def split_baselined(findings: Sequence[Finding],
                    baseline: Dict[str, dict]
                    ) -> Tuple[List[Finding], List[Finding]]:
    """(new, grandfathered) split by content fingerprint."""
    new, old = [], []
    for f in findings:
        (old if f.fingerprint in baseline else new).append(f)
    return new, old
