"""Runtime sanitizer mode (``FLAGS_sanitize``) for the serving stack.

The static passes (`paddle_tpu.analysis.passes`) prove what they can
about the SOURCE; this module catches the remainder at RUNTIME, turned
on by one flag and near-free when off:

* **use-after-donate** — `inference.serving._JitTracker` tombstones
  every donated argument after the call (`tombstone`), and any later
  host access raises `UseAfterDonateError` naming the donation site.
  On CPU, XLA silently ignores donation, so a read-after-donate bug
  passes every CPU test and corrupts data only on TPU — exactly the
  class a sanitizer must catch before hardware does;
* **lock-order cycles** — the designated telemetry locks are
  `TrackedLock` wrappers; while the sanitizer is active every
  acquisition records (held -> acquiring) edges in a process-wide
  order graph, and the edge that closes a cycle raises
  `LockOrderError` at the acquisition that would have deadlocked;
* **warm retraces raise** — `_JitTracker.check_retrace` raises
  `WarmRetraceError` instead of incrementing
  ``retraces_after_warmup``: the zero-warm-retrace contract becomes an
  assertion, not a counter someone has to read;
* **host-sync sentinel** — the engine's blocking device reads
  (`DecodeEngine._host_fetch`) are counted per serve, so a step that
  silently grew a second sync (an accidental ``int(traced)`` on the
  hot path) shows up in `report()` as ``host_syncs > steps``.

Everything routes through `active()`: ``None`` when the flag is off
(one dict lookup on the hot path), the process `Sanitizer` otherwise.
This module imports only the standard library at import time so the
lock wrappers can be constructed from `core.dispatch` and
`observability.metrics` without ordering constraints; the flag is read
lazily the first time `active()` runs after `core.flags` is populated.
"""
from __future__ import annotations

import threading
from typing import Optional

__all__ = [
    "SanitizerError", "UseAfterDonateError", "LockOrderError",
    "WarmRetraceError", "Sanitizer", "TrackedLock", "active", "get",
    "reset",
]


class SanitizerError(RuntimeError):
    """Base class: every sanitizer failure is loud and typed."""


class UseAfterDonateError(SanitizerError):
    """A donated (device-invalidated) buffer reached a host access or
    was fed back into an executable."""


class LockOrderError(SanitizerError):
    """Two designated locks were acquired in both orders — a latent
    deadlock."""


class WarmRetraceError(SanitizerError):
    """A warm executable recompiled mid-serve (the zero-warm-retrace
    contract, promoted from counter to assertion)."""


# flag wiring: installed lazily because this module must be importable
# before core.flags has defined FLAGS_sanitize (dispatch/metrics build
# their TrackedLocks at import time).  `active()` reads the flag
# REGISTRY directly (one dict lookup, no cached copy): set_flags
# mutates the registry before running its change callbacks, so there
# is no window where a callback (e.g. clear_dispatch_cache taking
# _CACHE_LOCK) observes a stale sanitize state.
_STATE = {"reg": None}


def _install() -> bool:
    if _STATE["reg"] is not None:
        return True
    try:
        from ..core import flags as _flags

        _flags.flag("sanitize")  # KeyError until flags.py has run
        _STATE["reg"] = _flags._REGISTRY
    except Exception:
        return False
    return True


class Sanitizer:
    """Process-wide sanitizer state: the lock-order graph, the donated-
    buffer tombstone registry, and the per-serve counters.  All methods
    are thread-safe (the engine steps on a worker thread under
    `ServingFrontend`)."""

    def __init__(self):
        self._mu = threading.Lock()  # guards graph + tombstones
        self._tls = threading.local()
        self.lock_edges = {}
        self.host_syncs = 0
        self.steps = 0
        self.warm_retraces = 0
        self._tombstones = {}

    def reset(self):
        """Drop all recorded global state (test isolation: edges and
        tombstones from one scenario must not fail the next).  Per-
        thread held-lock stacks are NOT touched — they self-maintain:
        release bookkeeping runs even while the sanitizer is disabled,
        so a flag flip mid-hold cannot leave a phantom entry."""
        with self._mu:
            self.lock_edges = {}
            self.host_syncs = 0
            self.steps = 0
            self.warm_retraces = 0
            self._tombstones = {}

    # -- counters (report() reads under _mu; writers must match) -------------
    def count_step(self):
        with self._mu:
            self.steps += 1

    def count_host_sync(self):
        with self._mu:
            self.host_syncs += 1

    def count_warm_retrace(self, n=1):
        with self._mu:
            self.warm_retraces += n

    def report(self) -> dict:
        with self._mu:
            return {
                "steps": self.steps,
                "host_syncs": self.host_syncs,
                "warm_retraces": self.warm_retraces,
                "lock_edges": sorted(self.lock_edges),
                "tombstoned_buffers": len(self._tombstones),
            }

    # -- lock-order tracking -------------------------------------------------
    def _held(self):
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h

    def note_acquire(self, name: str, reentrant: bool = True):
        """Record that this thread is about to acquire ``name``.  Adds
        (held -> name) edges for every currently-held lock; an edge
        that closes a cycle raises BEFORE the acquisition blocks.
        ``reentrant=False`` (a plain Lock): re-acquiring a lock this
        thread already holds is a guaranteed self-deadlock and raises
        immediately."""
        held = self._held()
        if name in held:
            if not reentrant:
                raise LockOrderError(
                    f"self-deadlock: thread already holds non-"
                    f"reentrant lock {name!r} and is acquiring it "
                    f"again — this blocks forever")
            held.append(name)  # RLock: no new ordering info
            return
        if held:
            with self._mu:
                for h in dict.fromkeys(held):
                    if (h, name) not in self.lock_edges:
                        cycle = self._path(name, h)
                        if cycle is not None:
                            # do NOT record the cycle-closing edge: the
                            # next occurrence of this inverted order
                            # must raise again, not sail past the check
                            # into the real deadlock
                            raise LockOrderError(
                                "lock-order cycle: acquiring "
                                f"{name!r} while holding {h!r}, but the "
                                "opposite order was already observed "
                                f"(path {' -> '.join([h, name] + cycle[1:])})"
                            )
                        self.lock_edges[(h, name)] = True
        held.append(name)

    def note_release(self, name: str):
        held = self._held()
        if held and held[-1] == name:
            held.pop()
        elif name in held:  # out-of-order release: still keep stack sane
            held.remove(name)

    def _path(self, start: str, target: str) -> Optional[list]:
        """Path start -> ... -> target in the edge graph, or None.
        Caller holds self._mu."""
        stack = [(start, [start])]
        seen = set()
        while stack:
            node, path = stack.pop()
            if node == target:
                return path
            if node in seen:
                continue
            seen.add(node)
            for (a, b) in self.lock_edges:
                if a == node:
                    stack.append((b, path + [b]))
        return None

    # -- use-after-donate ----------------------------------------------------
    # registry bound: a sanitized soak run tombstones ~2 buffers per
    # engine step; beyond the cap the OLDEST entries are dropped (their
    # pinned array shells become collectable, so the window of
    # site-attributed detection is bounded — jax's own deleted-buffer
    # error still fires on raw reads forever)
    MAX_TOMBSTONES = 4096

    def tombstone(self, arr, site: str):
        """Mark ``arr`` as donated at ``site``.  The array object is
        pinned while the entry lives (so its id cannot alias a newer
        allocation) and its device buffer is deleted when the backend
        supports it — a raw host read afterwards raises jax's own
        deleted-buffer error, while `check_live` raises with the
        donation site."""
        if arr is None:
            return
        with self._mu:
            self._tombstones[id(arr)] = (arr, site)
            while len(self._tombstones) > self.MAX_TOMBSTONES:
                self._tombstones.pop(next(iter(self._tombstones)))
        try:
            delete = getattr(arr, "delete", None)
            if delete is not None:
                delete()
        except Exception:
            pass  # already deleted / backend refuses: registry suffices

    def donation_site(self, arr) -> Optional[str]:
        with self._mu:
            hit = self._tombstones.get(id(arr))
        return None if hit is None else hit[1]

    def check_live(self, obj, context: str = "", _depth: int = 0):
        """Raise `UseAfterDonateError` if ``obj`` (or, for shallow
        containers, any leaf) was donated earlier.  Called by
        `_JitTracker` on every executable argument, so feeding a stale
        pre-donation reference back into a step fails at the call."""
        site = self.donation_site(obj)
        if site is not None:
            raise UseAfterDonateError(
                f"use after donate{': ' + context if context else ''} — "
                f"this buffer was donated at {site} and its device "
                f"memory has been reused; rebind to the executable's "
                f"returned arrays instead of holding the input")
        if _depth >= 3:
            return
        if isinstance(obj, dict):
            for v in obj.values():
                self.check_live(v, context, _depth + 1)
        elif isinstance(obj, (list, tuple)):
            for v in obj:
                self.check_live(v, context, _depth + 1)


_SAN = Sanitizer()


def get() -> Sanitizer:
    """The process sanitizer (state readable even while disabled)."""
    return _SAN


def active() -> Optional[Sanitizer]:
    """The process `Sanitizer` when FLAGS_sanitize is on, else None —
    THE hot-path check (a dict lookup once the flag registry exists)."""
    reg = _STATE["reg"]
    if reg is None:
        if not _install():
            return None
        reg = _STATE["reg"]
    return _SAN if reg["sanitize"] else None


def reset():
    _SAN.reset()


class TrackedLock:
    """Drop-in wrapper over a ``threading.Lock``/``RLock``: delegates
    acquire/release, and while the sanitizer is active records the
    acquisition order into the process-wide graph (cycles raise
    `LockOrderError`).  When the sanitizer is off the cost is one dict
    lookup per acquisition."""

    __slots__ = ("_inner", "name", "_reentrant")

    def __init__(self, inner, name: str):
        self._inner = inner
        self.name = name
        # a plain Lock self-deadlocks on same-thread re-acquisition —
        # note_acquire must raise there instead of treating it as
        # RLock reentrancy
        self._reentrant = isinstance(inner, type(threading.RLock()))

    def acquire(self, *args, **kwargs):
        san = active()
        if san is not None:
            # record (and cycle-check) BEFORE the acquisition can block
            san.note_acquire(self.name, reentrant=self._reentrant)
        try:
            ok = self._inner.acquire(*args, **kwargs)
        except BaseException:
            # interrupted while blocking (KeyboardInterrupt, pytest
            # timeout): the lock was never taken — the held-stack entry
            # must not outlive the failed acquisition
            _SAN.note_release(self.name)
            raise
        if not ok:
            # failed non-blocking try: the lock is not held — undo the
            # held-stack entry (no-op if the sanitizer was off above)
            _SAN.note_release(self.name)
        return ok

    def release(self):
        # held-stack bookkeeping runs UNCONDITIONALLY: if the flag
        # flips off between a thread's acquire and release, the entry
        # must still pop or it would haunt every later sanitized run
        # on this thread (note_release on an absent name is a no-op)
        _SAN.note_release(self.name)
        return self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"TrackedLock({self.name!r}, {self._inner!r})"
