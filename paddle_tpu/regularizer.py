"""Weight-decay regularizers.

Reference: `python/paddle/fluid/regularizer.py:50,157` — `L1Decay`/`L2Decay`
append a scaled penalty gradient to each parameter's gradient before the
optimizer update.  TPU-native: the regularizer is a pure function
``grad(p)`` folded into the (jit-compiled) optimizer update sweep instead of
a separate appended op, so XLA fuses it with the update kernel.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self):
        return self._coeff

    def __call__(self, param):
        """Return the penalty gradient for `param` (a jax array)."""
        raise NotImplementedError


class L1Decay(WeightDecayRegularizer):
    """loss += coeff * sum(|p|); grad contribution = coeff * sign(p)."""

    def __call__(self, param):
        return self._coeff * jnp.sign(param)


class L2Decay(WeightDecayRegularizer):
    """loss += 0.5 * coeff * sum(p^2); grad contribution = coeff * p."""

    def __call__(self, param):
        return self._coeff * param
