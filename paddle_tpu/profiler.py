"""Profiler surface.

Reference: `paddle/fluid/platform/profiler.{h,cc}` (`EnableProfiler`,
`DisableProfiler`, RAII `RecordEvent`, aggregated event tables, chrome-trace
timeline via `profiler.proto`) + `platform/device_tracer.cc` (CUPTI device
activity) + Python context managers `python/paddle/fluid/profiler.py`.

TPU-native split:
- **Host events** go through the native C++ tracer (csrc/runtime.cc Tracer:
  lock-free-ish append buffer, chrome-trace JSON export) via RecordEvent.
- **Device timeline** is XLA/PJRT's own tracing (TraceMe/xplane): wrapped by
  `start_trace`/`stop_trace` below (`jax.profiler`), viewable in
  TensorBoard/XProf — the moral replacement for the CUPTI DeviceTracer.
"""
from __future__ import annotations

import contextlib
import json
from collections import defaultdict

from .core import native
from .core.native import RecordEvent, now_ns  # re-export  # noqa: F401
# Eager dispatch telemetry (core/dispatch.py): per-op call/hit/miss/
# retrace counters + wall time for the signature-keyed executable cache —
# the dispatch-level complement of the host-event tables below.
from .core.dispatch import (  # noqa: F401
    clear_dispatch_cache, dispatch_cache_size, dispatch_stats,
    dispatch_summary_string, reset_dispatch_stats,
)

__all__ = [
    "RecordEvent", "profiler", "start_profiler", "stop_profiler",
    "reset_profiler", "start_trace", "stop_trace", "trace",
    "summary_string", "export_chrome_tracing",
    "dispatch_stats", "dispatch_summary_string", "reset_dispatch_stats",
    "clear_dispatch_cache", "dispatch_cache_size",
    "decode_stats", "reset_decode_stats",
]


# Decode-telemetry schema, shared with inference.serving (which builds
# its live counter dict from these) so the not-imported fallback below
# can never silently diverge from the real key set.
DECODE_STAT_COUNTERS = (
    "steps", "tokens", "prefills", "decode_time_s", "prefill_time_s",
    "decode_compiles", "prefill_compiles", "retraces_after_warmup",
    "occupancy_sum", "kv_util_sum",
    # chunked prefill (FLAGS_chunked_prefill): prompt chunks fused into
    # the decode step through the mixed-batch executable.
    # ``stalled_decode_steps`` counts legacy one-shot prefills that ran
    # while other slots were decoding (the stall chunking removes) —
    # it must stay 0 on the chunked path.
    "mixed_steps", "mixed_compiles", "prefill_chunks",
    "stalled_decode_steps",
    # prefix caching (FLAGS_prefix_cache): pages mapped from /
    # missing in the content-addressed cache at admission, prompt
    # tokens skipped, and unreferenced cached pages recycled under
    # pool pressure
    "prefix_hits", "prefix_misses", "prefix_cached_tokens",
    "prefix_evictions",
    # speculative decoding (inference.speculative): propose/verify loop
    "spec_steps", "spec_slot_steps", "spec_proposed", "spec_accepted",
    "spec_emitted",
    "draft_time_s", "verify_time_s", "verify_compiles", "draft_compiles",
    # request-completion accounting (Request.finish_reason; "cancelled"
    # counts queued AND running requests removed via Request.cancel())
    "finished_eos", "finished_length", "evicted", "cancelled",
    # SLO-aware scheduling (inference.frontend.SLOScheduler):
    # preempt/resume cycles, still-queued requests retired at their
    # deadline, and declared TTFT/TPOT/deadline targets missed
    "preemptions", "resumes", "deadline_expired", "slo_violations",
    # fault containment + crash recovery (inference.resilience):
    # injected faults fired, same-step retries spent, requests
    # quarantined with finish_reason="fault", engine rebuilds, and
    # degraded-mode transitions (speculation disabled / chunked
    # prefill fallen back to the legacy oracle path)
    "faults_injected", "step_retries", "finished_fault", "recoveries",
    "spec_disables", "legacy_fallbacks",
    # durable serving (inference.durability): write-ahead journal
    # records appended, on-disk snapshots written, fresh-process
    # restores performed, executables handed from a dead engine to its
    # rebuilt successor (recompiles avoided), and steps the watchdog
    # classified as hung (FLAGS_step_timeout_ms)
    "journal_records", "journal_snapshots", "restores", "exec_handoffs",
    "hung_steps",
    # fleet serving (paddle_tpu.fleet): a dead replica's journal
    # replayed into a LIVE survivor engine (zero-loss failover), and
    # journals rewritten down to their live state during restore
    # (FLAGS_journal_compact)
    "adoptions", "journal_compactions",
    # flight recorder (observability.flight): sealed per-step records
    # pushed into the bounded ring, and crash-safe window auto-dumps
    # (fatal fault / hung step / watchdog abandonment black boxes)
    "flight_records", "flight_dumps",
    # quantized KV pages (FLAGS_kv_quant=int8): pages whose quant
    # scale was (re)initialized on allocation ("pages quantized"),
    # (page, head) scale entries re-quantized after an absmax growth
    # (the write-path "refold"), and the tiny scale-reset executable's
    # compiles (target pool + draft pool, one signature each)
    "kv_quant_pages", "kv_quant_refolds", "kv_quant_compiles",
    # quantized weight storage (FLAGS_serve_weights=int8): matmul
    # weight matrices folded to int8 + per-out-channel f32 scales at
    # engine construction / drafter bind ("mats"), and the HBM bytes
    # that fold reclaimed net of the scale leaves it added — both stay
    # 0 on serve_weights=off engines (the off-mode-quiet proof the
    # bench's parity leg pins)
    "weight_quant_mats", "weight_quant_bytes_saved",
    # cost observatory (observability.costmodel): static FLOP/byte
    # profiles extracted at executable compile time, and calibration
    # updates scored against the flight recorder's measured steps
    "cost_profiles", "cost_updates",
    # profiling plane (observability.profiling): steps whose device
    # dispatches were sync-probed (FLAGS_profile_sample_steps cadence
    # or an armed capture), and bounded capture sessions completed
    "profile_probes", "profile_captures",
    # ragged unified step (FLAGS_ragged_step): compiles of the ONE
    # executable that serves decode, mixed prefill+decode, and
    # speculative verify traffic alike (every row carries its own
    # query span), and the adaptive per-slot speculation depth's
    # shrink/grow transitions (FLAGS_spec_adaptive_k)
    "ragged_compiles", "spec_k_shrinks", "spec_k_grows",
    # per-executable retrace attribution: ``retraces_after_warmup``
    # aggregates every site; these split the same events by the
    # tracker's compile_key (<kind>_compiles -> <kind>_retraces), so
    # "the ragged path compiles exactly one step executable and never
    # retraces it" is a counter assertion, not a log grep
    "decode_retraces", "prefill_retraces", "mixed_retraces",
    "verify_retraces", "draft_retraces", "kv_quant_retraces",
    "ragged_retraces",
)
DECODE_STAT_DERIVED = ("avg_step_ms", "batch_occupancy",
                       "kv_block_utilization",
                       "acceptance_rate", "mean_accepted_per_step")


def _decode_stat_zero(key):
    return 0.0 if key.endswith(("_s", "_sum", "_ms")) or \
        key in DECODE_STAT_DERIVED else 0


def decode_stats(reset=False):
    """Serving-loop telemetry (inference.serving.DecodeEngine): decode
    step latency, batch occupancy, KV-block utilization, executable
    compile/retrace counts.  If no engine was ever created in this
    process, returns all-zero counters WITHOUT importing the serving
    module (a telemetry poller must not pay the engine's import)."""
    import sys

    mod = sys.modules.get("paddle_tpu.inference.serving")
    if mod is None:
        return {k: _decode_stat_zero(k)
                for k in DECODE_STAT_COUNTERS + DECODE_STAT_DERIVED}
    return mod.decode_stats(reset)


def reset_decode_stats():
    import sys

    mod = sys.modules.get("paddle_tpu.inference.serving")
    if mod is not None:
        mod.reset_decode_stats()


_state = {"device": False}


def start_profiler(state="All", tracer_option="Default"):
    """Begin host event collection (reference `EnableProfiler`,
    `fluid/profiler.py start_profiler`).  `state`/`tracer_option` are
    accepted for API compatibility; device-side tracing is a separate
    concern on TPU — use `start_trace`/`trace` for the XLA timeline."""
    native.trace_clear()
    native.tracer_enable()


def stop_profiler(sorted_key="total", profile_path=None, print_table=True):
    """Stop collection; print the aggregated table; optionally dump a
    chrome-trace timeline json to `profile_path` (reference
    `DisableProfiler` + timeline proto export).  ``print_table=False``
    returns the table without writing stdout — for tests and the
    periodic observability reporter, which collect rather than spam;
    the default keeps the reference's print-on-stop behavior."""
    native.tracer_disable()
    text = summary_string(sorted_key=sorted_key)
    if print_table:
        print(text)
    if profile_path:
        export_chrome_tracing(profile_path)
    return text


def reset_profiler():
    native.trace_clear()


@contextlib.contextmanager
def profiler(state="All", sorted_key="total", profile_path=None):
    """Context-manager form (reference `fluid/profiler.py profiler`)."""
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


# ---------------------------------------------------------------------------
# aggregation / export
# ---------------------------------------------------------------------------
def _events():
    data = json.loads(native.trace_export_json())
    return data.get("traceEvents", [])


def summary_string(sorted_key="total") -> str:
    """Aggregated per-event table: calls, total/avg/min/max ms, ratio —
    the layout of the reference's `PrintProfiler` table."""
    agg = defaultdict(lambda: [0, 0.0, float("inf"), 0.0])  # n, tot, mn, mx
    for ev in _events():
        if ev.get("ph") != "X":
            continue
        dur_ms = ev.get("dur", 0) / 1000.0  # chrome trace dur is us
        a = agg[ev.get("name", "?")]
        a[0] += 1
        a[1] += dur_ms
        a[2] = min(a[2], dur_ms)
        a[3] = max(a[3], dur_ms)
    total = sum(a[1] for a in agg.values()) or 1.0
    keyfn = {
        "total": lambda kv: -kv[1][1],
        "calls": lambda kv: -kv[1][0],
        "max": lambda kv: -kv[1][3],
        "min": lambda kv: kv[1][2],
        "ave": lambda kv: -(kv[1][1] / max(kv[1][0], 1)),
    }.get(sorted_key, lambda kv: -kv[1][1])
    lines = [
        "-------------------------     Profiling Report     "
        "-------------------------",
        f"{'Event':<40}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>10}"
        f"{'Min(ms)':>10}{'Max(ms)':>10}{'Ratio':>8}",
    ]
    for name, (n, tot, mn, mx) in sorted(agg.items(), key=keyfn):
        lines.append(
            f"{name:<40}{n:>8}{tot:>12.4f}{tot / max(n, 1):>10.4f}"
            f"{mn if n else 0:>10.4f}{mx:>10.4f}{tot / total:>8.2%}")
    return "\n".join(lines)


def export_chrome_tracing(path: str):
    """Write the MERGED chrome://tracing JSON: host tracer events plus
    the observability span tracks (engine decode/prefill/verify step
    spans, per-request lifecycle spans) on separately named process
    lanes (reference timeline proto → `tools/timeline.py` equivalent,
    now one timeline for all three telemetry sources).  A process that
    recorded no spans gets exactly the old host-only timeline plus its
    track label."""
    from .observability import tracing as _tracing

    _tracing.export_chrome_trace(path)


# ---------------------------------------------------------------------------
# device (XLA) tracing
# ---------------------------------------------------------------------------
def start_trace(log_dir: str):
    """Start an XLA/PJRT device trace (xplane, TensorBoard-viewable) —
    the TPU replacement for the reference's CUPTI DeviceTracer
    (`platform/device_tracer.cc:57`)."""
    import jax

    jax.profiler.start_trace(log_dir)
    _state["device"] = True


def stop_trace():
    import jax

    if _state["device"]:
        jax.profiler.stop_trace()
        _state["device"] = False


@contextlib.contextmanager
def trace(log_dir: str):
    start_trace(log_dir)
    try:
        yield
    finally:
        stop_trace()
